"""Cluster event stream (events/broker.py + /v1/event/stream): broker
semantics, FSM-sourced emission, the chunked-HTTP and websocket tiers
through the real in-proc server, per-topic ACL enforcement, resume from
index, and the slow-consumer / lost-gap contracts."""

import json
import threading
import time

import pytest

import nomad_tpu.mock as mock
from nomad_tpu.api.client import APIError, ApiClient
from nomad_tpu.api.http import HTTPServer
from nomad_tpu.api.ws import WsClient
from nomad_tpu.core import fsm as fsm_mod
from nomad_tpu.core.server import Server
from nomad_tpu.events import (
    ALL_TOPICS,
    Event,
    EventBroker,
    SubscriptionClosedError,
)
from nomad_tpu.raft import InmemTransport, RaftConfig


def wait_until(fn, timeout=15.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


def make_server(extra=None):
    cfg = {
        "seed": 42,
        "heartbeat_ttl": 600.0,
        "raft": {
            "node_id": "s0",
            "address": "raft0",
            "voters": {"s0": "raft0"},
            "transport": InmemTransport(),
            "config": RaftConfig(
                heartbeat_interval=0.02,
                election_timeout_min=0.05,
                election_timeout_max=0.10,
            ),
        },
    }
    cfg.update(extra or {})
    s = Server(cfg)
    s.start(num_workers=1, wait_for_leader=5.0)
    return s


def ev(index, topic="Job", type="JobRegistered", key="j1", ns="default"):
    return Event(topic=topic, type=type, key=key, index=index, namespace=ns)


class TestEventBrokerUnit:
    def test_publish_fanout_in_index_order(self):
        b = EventBroker(size=100)
        sub = b.subscribe()
        for i in range(1, 6):
            b.publish(i, [ev(i)])
        seen = []
        while True:
            frame = sub.next(timeout=0.1)
            if frame is None:
                break
            idx, events = frame
            assert events is not None
            assert all(e.index == idx for e in events)
            seen.append(idx)
        assert seen == [1, 2, 3, 4, 5]

    def test_topic_and_key_filters(self):
        b = EventBroker(size=100)
        only_j2 = b.subscribe({"Job": {"j2"}})
        only_nodes = b.subscribe({"Node": {"*"}})
        b.publish(1, [ev(1, key="j1")])
        b.publish(2, [ev(2, key="j2")])
        b.publish(3, [ev(3, topic="Node", type="NodeRegistration", key="n1")])
        idx, events = only_j2.next(timeout=0.5)
        assert idx == 2 and events[0].key == "j2"
        assert only_j2.next(timeout=0.05) is None
        idx, events = only_nodes.next(timeout=0.5)
        assert idx == 3 and events[0].topic == "Node"

    def test_filter_keys_match_secondary_ids(self):
        b = EventBroker(size=100)
        by_deploy = b.subscribe({"Alloc": {"dep-1"}})
        b.publish(
            1,
            [
                Event(
                    topic="Alloc", type="AllocationUpdated", key="a1",
                    index=1, namespace="default",
                    filter_keys=("job-1", "dep-1"),
                )
            ],
        )
        b.publish(
            2,
            [
                Event(
                    topic="Alloc", type="AllocationUpdated", key="a2",
                    index=2, namespace="default", filter_keys=("job-2",),
                )
            ],
        )
        idx, events = by_deploy.next(timeout=0.5)
        assert idx == 1 and events[0].key == "a1"
        assert by_deploy.next(timeout=0.05) is None

    def test_resume_replays_only_after_index(self):
        b = EventBroker(size=100)
        for i in range(1, 8):
            b.publish(i, [ev(i)])
        sub = b.subscribe(from_index=4)
        seen = []
        while True:
            frame = sub.next(timeout=0.1)
            if frame is None:
                break
            seen.append(frame[0])
        assert seen == [5, 6, 7]

    def test_ring_overflow_yields_explicit_gap(self):
        b = EventBroker(size=3)
        for i in range(1, 10):
            b.publish(i, [ev(i)])
        sub = b.subscribe(from_index=1)
        idx, events = sub.next(timeout=0.5)
        assert events is None, "first frame must be the lost-gap marker"
        assert idx >= 6  # events ≤ idx were overwritten
        rest = []
        while True:
            frame = sub.next(timeout=0.1)
            if frame is None:
                break
            rest.append(frame[0])
        assert rest == sorted(rest) and rest[-1] == 9
        assert rest[0] == idx + 1

    def test_slow_consumer_closed_with_resume_index(self):
        b = EventBroker(size=100, subscriber_buffer=4)
        sub = b.subscribe()
        for i in range(1, 10):
            b.publish(i, [ev(i)])
        # queue cap 4: the subscriber was closed, not buffered unboundedly
        drained = 0
        with pytest.raises(SubscriptionClosedError) as e:
            while True:
                if sub.next(timeout=0.1) is None:
                    break
                drained += 1
        assert drained <= 4
        # the advertised resume is a FLOOR: reconnecting with it replays
        # every frame the ring still retains (from_index is exclusive)
        resume = e.value.resume_index
        assert resume < b.oldest_index()
        sub2 = b.subscribe(from_index=resume, max_queued=100)
        idx, events = sub2.next(timeout=0.5)
        assert events is not None, "resume at the floor must not re-gap"
        assert idx == b.oldest_index(), "oldest retained frame replayed"
        assert b.stats()["slow_consumers_closed"] == 1

    def test_huge_replay_trims_to_newest_instead_of_closing(self):
        # an index-less subscriber on a busy cluster must reach the live
        # tail: the replay caps at the newest frames, silently for a
        # fresh subscribe, with an explicit gap for an explicit resume
        b = EventBroker(size=10000, subscriber_buffer=8)
        for i in range(1, 101):
            b.publish(i, [ev(i)])
        fresh = b.subscribe()
        idx, events = fresh.next(timeout=0.5)
        assert events is not None, "fresh subscribe must not start gapped"
        assert idx > 90, "replay kept the newest frames"
        assert not fresh.closed
        b.publish(101, [ev(101)])
        seen = []
        while True:
            frame = fresh.next(timeout=0.2)
            if frame is None:
                break
            seen.append(frame[0])
        assert seen[-1] == 101, "live publishes reach the subscriber"
        resumer = b.subscribe(from_index=5)
        idx, events = resumer.next(timeout=0.5)
        assert events is None, "explicit resume sees the trim as a gap"
        assert idx > 5

    def test_ring_wraparound_at_exact_boundary(self):
        # ref event_buffer_test.go: fill the ring to EXACTLY its size,
        # then one more — the oldest frame (and only it) is evicted and
        # the watermark lands on its index, not one off
        b = EventBroker(size=5)
        for i in range(1, 6):
            b.publish(i, [ev(i)])
        assert b.oldest_index() == 1
        assert b.stats()["events_buffered"] == 5
        assert b._dropped_through == 0
        b.publish(6, [ev(6)])
        assert b.oldest_index() == 2
        assert b.stats()["events_buffered"] == 5
        assert b._dropped_through == 1
        # resume exactly at the watermark: complete replay, no gap frame
        sub = b.subscribe(from_index=1)
        seen = []
        while True:
            frame = sub.next(timeout=0.1)
            if frame is None:
                break
            idx, events = frame
            assert events is not None, "boundary resume must not gap"
            seen.append(idx)
        assert seen == [2, 3, 4, 5, 6]
        # one more publish moves the watermark to 2; an explicit resume
        # one index BELOW it is a real gap — at the exact boundary, not
        # one off
        b.publish(7, [ev(7)])
        assert b._dropped_through == 2
        at_floor = b.subscribe(from_index=2)
        idx, events = at_floor.next(timeout=0.1)
        assert events is not None and idx == 3, "boundary resume gapped"
        below_floor = b.subscribe(from_index=1)
        idx, events = below_floor.next(timeout=0.1)
        assert events is None, "stale resume must surface the gap"
        assert idx == 2

    def test_subscriber_close_under_publish_race(self):
        # ref subscription_test.go close-during-delivery: subscribers
        # closing (and churning) while a publisher floods must neither
        # deadlock nor leak registrations nor deliver to closed queues
        b = EventBroker(size=1000, subscriber_buffer=8)
        stop = threading.Event()
        errors = []

        def publisher():
            i = 0
            while not stop.is_set():
                i += 1
                try:
                    b.publish(i, [ev(i)])
                except Exception as e:  # pragma: no cover - the assert
                    errors.append(e)

        def churner(cid):
            try:
                for _ in range(50):
                    sub = b.subscribe()
                    sub.next(timeout=0.001)
                    sub.close()
            except SubscriptionClosedError:
                pass
            except Exception as e:  # pragma: no cover - the assert
                errors.append(e)

        threads = [
            threading.Thread(
                target=publisher, name="race-pub", daemon=True
            )
        ] + [
            threading.Thread(
                target=churner, args=(c,), name=f"race-sub-{c}",
                daemon=True,
            )
            for c in range(4)
        ]
        for t in threads:
            t.start()
        time.sleep(0.5)
        stop.set()
        for t in threads:
            t.join(timeout=5.0)
            assert not t.is_alive(), "deadlocked under close/publish race"
        assert not errors, errors
        assert b.stats()["subscribers"] == 0, "closed subs left registered"

    def test_per_event_acl_filtering_across_namespaces(self):
        # broker-level slice of the event_endpoint ACL contract: one
        # subscription spanning namespaces only sees events its token's
        # capabilities cover, re-checked per event at delivery
        class FakeACL:
            management = False

            def allow_node_read(self):
                return False

            def allow_namespace_operation(self, ns, cap):
                return ns == "default" and cap == "read-job"

        b = EventBroker(size=100)
        sub = b.subscribe(acl=FakeACL(), namespace="*")
        b.publish(1, [ev(1, key="mine", ns="default")])
        b.publish(2, [ev(2, key="theirs", ns="ops")])
        b.publish(3, [ev(3, topic="Node", type="NodeRegistration",
                         key="n1", ns="")])
        b.publish(4, [ev(4, key="mine-too", ns="default")])
        seen = []
        while True:
            frame = sub.next(timeout=0.1)
            if frame is None:
                break
            seen.extend(e.key for e in frame[1])
        assert seen == ["mine", "mine-too"], seen

    def test_reset_closes_subscribers_at_restored_index(self):
        b = EventBroker(size=100)
        sub = b.subscribe()
        b.publish(1, [ev(1)])
        sub.next(timeout=0.5)
        b.reset(41)
        with pytest.raises(SubscriptionClosedError) as e:
            sub.next(timeout=0.5)
        assert e.value.resume_index == 41
        # post-reset publishes reach new subscribers only
        sub2 = b.subscribe()
        b.publish(42, [ev(42)])
        idx, _ = sub2.next(timeout=0.5)
        assert idx == 42


class TestFsmEmission:
    def test_apply_tags_events_with_raft_index(self):
        from nomad_tpu.core.fsm import FSM

        broker = EventBroker(size=100)
        f = FSM(event_broker=broker)
        sub = broker.subscribe()
        node = mock.node()
        f.apply(7, fsm_mod.NODE_REGISTER, {"node": node.to_dict()})
        f.apply(
            8, fsm_mod.NODE_EVENTS_UPSERT,
            {"events": {node.id: [
                {"subsystem": "Driver", "message": "boom", "timestamp": 1}
            ]}},
        )
        idx, events = sub.next(timeout=0.5)
        assert idx == 7
        assert events[0].topic == "Node"
        assert events[0].type == "NodeRegistration"
        assert events[0].key == node.id
        idx, events = sub.next(timeout=0.5)
        assert idx == 8
        assert events[0].topic == "NodeEvent"
        assert events[0].payload["Events"][0]["message"] == "boom"

    def test_restore_resets_broker_to_state_index(self):
        from nomad_tpu.core.fsm import FSM

        broker = EventBroker(size=100)
        f = FSM(event_broker=broker)
        f.apply(3, fsm_mod.JOB_REGISTER, {"job": mock.job().to_dict()})
        snap = f.snapshot()
        sub = broker.subscribe()
        f2 = FSM(event_broker=broker)
        f2.restore(snap)
        # already-queued frames drain first; then the reset close surfaces
        with pytest.raises(SubscriptionClosedError) as e:
            while True:
                sub.next(timeout=0.5)
        assert e.value.resume_index == f2.state.latest_index()


class TestEventStreamE2E:
    """The acceptance path: register a job through the real in-proc
    server and watch Job/Eval/PlanResult/Alloc (plus Node/NodeEvent/
    Deployment) frames arrive over /v1/event/stream, index-ordered."""

    def setup_method(self):
        self.server = make_server()
        self.http = HTTPServer(self.server, port=0)
        self.http.start()
        self.client = ApiClient(address=self.http.address)

    def teardown_method(self):
        self.http.stop()
        self.server.stop()

    def _drive_all_topics(self):
        node = mock.node()
        self.server.node_register(node)
        job = mock.job()
        job.task_groups[0].tasks[0].resources.networks = []
        self.client.register_job(job.to_dict())
        wait_until(
            lambda: self.server.state.allocs_by_job("default", job.id),
            msg="allocs placed",
        )
        # node-operational + deployment entries ride the same log
        self.server._apply(
            fsm_mod.NODE_EVENTS_UPSERT,
            {"events": {node.id: [
                {"subsystem": "Driver", "message": "health flap",
                 "timestamp": 1}
            ]}},
        )
        self.server._apply(
            fsm_mod.DEPLOYMENT_STATUS_UPDATE,
            {"update": {
                "deployment_id": "dep-e2e", "status": "running",
                "status_description": "Deployment is running",
            }},
        )
        return job

    def test_all_seven_topics_index_ordered(self):
        stream = self.client.event_stream(heartbeat=0.2)
        frames = []
        done = threading.Event()

        def drain():
            for frame in stream:
                frames.append(frame)
                topics = {
                    e["Topic"] for f in frames for e in f.get("Events", [])
                }
                if len(topics) >= 7:
                    done.set()
                    return

        t = threading.Thread(target=drain, daemon=True)
        t.start()
        self._drive_all_topics()
        assert done.wait(15.0), (
            "topics seen: "
            + str({e["Topic"] for f in frames for e in f.get("Events", [])})
        )
        stream.close()
        topics = {e["Topic"] for f in frames for e in f.get("Events", [])}
        assert topics == set(ALL_TOPICS)
        # index-ordered frames; every event tagged with its frame index
        indexes = [f["Index"] for f in frames if f.get("Events")]
        assert indexes == sorted(indexes)
        for f in frames:
            for e in f.get("Events", []):
                assert e["Index"] == f["Index"]

    def test_resume_from_index_after_disconnect_no_dupes_no_loss(self):
        # snapshot=False: this test pins the raw ring's replay/resume
        # contract (a cold subscribe with snapshots on starts from a
        # state snapshot instead of replaying retained frames — that
        # path has its own tests in test_fanout.py)
        job = self._drive_all_topics()
        stream = self.client.event_stream(heartbeat=0.2, snapshot=False)
        first = []
        for frame in stream:
            if frame.get("Events"):
                first.append(frame)
            if len(first) >= 2:
                break
        stream.close()  # severed mid-stream
        cut = stream.last_index
        assert cut > 0
        # more writes while disconnected
        self.client.deregister_job(job.id)
        wait_until(
            lambda: self.server.state.latest_index() > cut + 1,
            msg="more writes applied",
        )
        resumed = self.client.event_stream(index=cut, heartbeat=0.2)
        seen = []
        deadline = time.monotonic() + 10
        for frame in resumed:
            if frame.get("Events"):
                seen.append(frame["Index"])
                if any(
                    e["Type"] == "JobDeregistered"
                    for e in frame["Events"]
                ):
                    break
            if time.monotonic() > deadline:
                break
        resumed.close()
        assert seen, "resumed stream delivered nothing"
        assert all(i > cut for i in seen), (cut, seen)
        assert seen == sorted(seen)
        # exactly-once across the sever: the resumed indexes pick up at
        # the very next applied index after the cut
        assert seen[0] == cut + 1

    def test_topic_filter_only_matching_frames(self):
        stream = self.client.event_stream(
            topics=["Eval", "Job:specific-job"], heartbeat=0.2
        )
        collected = []
        done = threading.Event()

        def drain():
            for frame in stream:
                for e in frame.get("Events", []):
                    collected.append(e)
                    if e["Topic"] == "Eval":
                        done.set()

        threading.Thread(target=drain, daemon=True).start()
        self.server.node_register(mock.node())
        job = mock.job()
        job.task_groups[0].tasks[0].resources.networks = []
        self.client.register_job(job.to_dict())
        assert done.wait(10.0)
        stream.close()
        assert collected, "no events matched"
        for e in collected:
            assert e["Topic"] == "Eval" or (
                e["Topic"] == "Job" and e["Key"] == "specific-job"
            ), e

    def test_unknown_topic_rejected(self):
        with pytest.raises(APIError) as e:
            self.client.event_stream(topics=["Bogus"])
        assert e.value.status == 400

    def test_lost_gap_frame_when_ring_overwrote(self):
        # tiny ring: writes while disconnected overrun retention. With
        # snapshots off the resume sees the explicit lost-gap marker;
        # with them on (the default) the same resume upgrades to
        # snapshot-at-N + deltas — never a silent skip either way.
        self.server.event_broker.size = 4
        job = self._drive_all_topics()
        for i in range(12):
            self.server._apply(
                fsm_mod.NODE_EVENTS_UPSERT,
                {"events": {"n-x": [
                    {"subsystem": "t", "message": str(i), "timestamp": i}
                ]}},
            )
        stream = self.client.event_stream(
            index=1, heartbeat=0.2, snapshot=False
        )
        frame = next(iter(stream))
        stream.close()
        assert frame.get("LostGap") is True
        assert frame.get("Index", 0) > 1
        # the carried floor is the resume point (the client tracks it:
        # resuming from the stale index would replay the gap forever)
        assert stream.last_index == frame["Index"]
        assert job is not None

    def test_gap_resume_upgrades_to_snapshot_plus_deltas(self):
        # the mirror's sync contract generalized into the stream: a
        # resume past the ring's retention starts from a state snapshot
        # stamped at raft index N instead of a lost-gap bail
        self.server.event_broker.size = 4
        self._drive_all_topics()
        for i in range(12):
            self.server._apply(
                fsm_mod.NODE_EVENTS_UPSERT,
                {"events": {"n-x": [
                    {"subsystem": "t", "message": str(i), "timestamp": i}
                ]}},
            )
        stream = self.client.event_stream(index=1, heartbeat=0.2)
        frames = []
        for frame in stream:
            frames.append(frame)
            if frame.get("SnapshotDone"):
                break
        # the snapshot leads; no gap bail BEFORE it (the marker for the
        # genuinely-lost ephemeral history rides after the sync — the
        # wildcard subscription spans NodeEvent, whose evicted ring
        # history no snapshot can heal, so it IS declared, later)
        assert not any(f.get("LostGap") for f in frames)
        done = frames[-1]
        stamp = done["Index"]
        assert stamp >= self.server.event_broker.oldest_index() - 1
        assert stream.last_index == stamp
        # snapshot batches carry the live state documents, stamped <= N
        snap_events = [
            e
            for f in frames
            if f.get("Snapshot")
            for e in f["Events"]
        ]
        assert snap_events, "snapshot carried no state"
        assert all(e["Index"] <= stamp for e in snap_events)
        assert all(e["Type"].endswith("Snapshot") for e in snap_events)
        # deltas ride from N: new writes arrive as ordinary frames
        self.server._apply(
            fsm_mod.NODE_EVENTS_UPSERT,
            {"events": {"n-y": [
                {"subsystem": "t", "message": "after", "timestamp": 99}
            ]}},
        )
        delta = None
        saw_gap = False
        saw_replay = False
        for frame in stream:
            if frame.get("LostGap"):
                # the evicted ephemeral (NodeEvent) history is declared,
                # not silently skipped — the snapshot can't carry it
                saw_gap = True
                continue
            if frame.get("Events") and not frame.get("Snapshot"):
                if frame["Index"] <= stamp:
                    # still-retained ephemeral ring history replays
                    # through the snapshot's dedupe floor
                    saw_replay = True
                    assert {
                        e["Topic"] for e in frame["Events"]
                    } <= {"NodeEvent", "PlanResult"}, frame
                    continue
                delta = frame
                break
        stream.close()
        assert saw_gap, "lost ephemeral history must be declared"
        assert saw_replay, "retained ephemeral history must replay"
        assert delta is not None and delta["Index"] > stamp

    def test_websocket_tier_serves_same_frames(self):
        ws = WsClient(
            f"127.0.0.1:{self.http.port}",
            "/v1/event/stream?heartbeat=0.2&topic=Job",
        )
        try:
            self.server.node_register(mock.node())
            job = mock.job()
            job.task_groups[0].tasks[0].resources.networks = []
            self.client.register_job(job.to_dict())
            deadline = time.monotonic() + 10
            frame = None
            while time.monotonic() < deadline:
                doc = json.loads(ws.recv(timeout=5.0).decode())
                if doc.get("Events"):
                    frame = doc
                    break
            assert frame is not None, "no event frame over websocket"
            assert frame["Events"][0]["Topic"] == "Job"
            assert frame["Events"][0]["Key"] == job.id
        finally:
            ws.close()

    def test_metrics_exposes_event_broker_stats(self):
        self._drive_all_topics()
        stats = self.client.metrics()["event_broker"]
        assert stats["events_published"] > 0
        assert stats["latest_index"] > 0


class TestEventStreamACL:
    def setup_method(self):
        self.server = make_server(extra={"acl": {"enabled": True}})
        self.http = HTTPServer(self.server, port=0)
        self.http.start()
        anon = ApiClient(address=self.http.address)
        boot = anon.put("/v1/acl/bootstrap")[0]
        self.mgmt = ApiClient(address=self.http.address, token=boot["SecretID"])
        self.mgmt.put(
            "/v1/acl/policy/readonly",
            body={"Rules": 'namespace "default" { policy = "read" }'},
        )
        tok = self.mgmt.put(
            "/v1/acl/token",
            body={"Name": "ro", "Type": "client", "Policies": ["readonly"]},
        )[0]
        self.ro = ApiClient(address=self.http.address, token=tok["SecretID"])

    def teardown_method(self):
        self.http.stop()
        self.server.stop()

    def test_anonymous_denied(self):
        anon = ApiClient(address=self.http.address)
        with pytest.raises(APIError) as e:
            anon.event_stream(topics=["Job"])
        assert e.value.status == 403

    def test_node_topic_needs_node_read(self):
        with pytest.raises(APIError) as e:
            self.ro.event_stream(topics=["Node"])
        assert e.value.status == 403
        with pytest.raises(APIError) as e:
            self.ro.event_stream(topics=["Job", "NodeEvent"])
        assert e.value.status == 403

    def test_wildcard_topic_needs_union_of_capabilities(self):
        # "*" spans node-scoped topics, which this token can't read
        with pytest.raises(APIError) as e:
            self.ro.event_stream()
        assert e.value.status == 403
        # management sees everything
        stream = self.mgmt.event_stream(heartbeat=0.2)
        stream.close()

    def test_acl_write_closes_token_backed_streams(self):
        # a revoked/changed token must not keep streaming on old grants:
        # ACL writes close every token-backed subscription (resumable)
        stream = self.ro.event_stream(topics=["Job"], heartbeat=0.2)
        self.mgmt.put(
            "/v1/acl/policy/other",
            body={"Rules": 'namespace "x" { policy = "read" }'},
        )
        got_error = None
        deadline = time.monotonic() + 10
        for frame in stream:
            if frame.get("Error"):
                got_error = frame
                break
            if time.monotonic() > deadline:
                break
        stream.close()
        assert got_error is not None, "stream survived an ACL change"
        assert "ACL" in got_error["Error"]
        assert "ResumeIndex" in got_error

    def test_acl_change_leaves_in_proc_subscriptions_alone(self):
        # acl=None consumers (deployment watcher et al.) are not
        # token-backed and must survive ACL churn
        sub = self.server.event_broker.subscribe()
        self.mgmt.put(
            "/v1/acl/policy/another",
            body={"Rules": 'namespace "y" { policy = "read" }'},
        )
        assert not sub.closed
        sub.close()

    def test_namespaced_topics_filtered_per_event(self):
        stream = self.ro.event_stream(
            topics=["Job"], namespace="*", heartbeat=0.2
        )
        got = []
        done = threading.Event()

        def drain():
            for frame in stream:
                for e in frame.get("Events", []):
                    got.append(e)
                    if e["Key"] == "visible-job":
                        done.set()

        threading.Thread(target=drain, daemon=True).start()
        secret = mock.job()
        secret.id = secret.name = "secret-job"
        secret.namespace = "ops"
        secret.task_groups[0].tasks[0].resources.networks = []
        self.server.job_register(secret)
        visible = mock.job()
        visible.id = visible.name = "visible-job"
        visible.task_groups[0].tasks[0].resources.networks = []
        self.server.job_register(visible)
        assert done.wait(10.0)
        stream.close()
        keys = {e["Key"] for e in got}
        assert "visible-job" in keys
        assert "secret-job" not in keys, (
            "event from an unauthorized namespace leaked"
        )


class TestDeploymentWatcherOnStream:
    def test_watcher_subscribes_instead_of_polling(self):
        server = make_server()
        try:
            assert server.event_broker is not None
            wait_until(
                lambda: server.event_broker.stats()["subscribers"] >= 1,
                timeout=5.0,
                msg="deployments-watcher manager subscription",
            )
        finally:
            server.stop()

    def test_watcher_falls_back_to_blocking_query(self):
        server = make_server(extra={"event_broker": {"enabled": False}})
        try:
            assert server.event_broker is None
            # deployment machinery still runs on the poll path
            assert server.deployment_watcher is not None
        finally:
            server.stop()
