"""Multi-eval kernel batching for the eval-broker drain.

The reference schedules with one worker goroutine per core, each planning a
single evaluation against its own snapshot (worker.go:105-276). The TPU
bridge instead drains N evaluations at once (SURVEY §2.3: "this is where the
TPU bridge drains N evals at a time"): each eval still runs its full
scheduler bookkeeping — reconciler, plan construction, blocked evals,
individual plan submission and ack/nack — on its own thread, but the
placement scans all park at a :class:`KernelBatchCollector`, which fuses
them into ONE multi-eval ``plan_batch`` program (kernel.py: per-eval ring
permutations/cursors over a shared capacity plane) and hands each eval its
slice of the placements.

Because the fused scan threads capacity sequentially across evals (in
dequeue/priority order), the combined plans never oversubscribe each other
— the batch behaves like the serialized plan applier would, instead of N
optimistic plans racing and partially rejecting.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass
from typing import Optional

import numpy as np

from .columnar import ColumnarCluster, GroupPlanes

logger = logging.getLogger("nomad_tpu.tpu.drain")

#: stats of the most recent drain invocation (benchmark/observability)
# nta: ignore[unbounded-cache] WHY: fixed stat-name keys, overwritten
# per drain invocation
LAST_DRAIN_STATS: dict = {}

#: cumulative drain accounting (observability / tests)
DRAIN_COUNTERS = {"batches": 0, "evals": 0}


class SharedCluster:
    """The node-axis arrays every eval in a drain batch shares (per-eval DC
    eligibility lives in each eval's ring permutation), their capacity
    planes, and the snapshot usage.

    With a :class:`~..tpu.mirror.ColumnarMirror` (the server path), the
    arrays alias the store's COMMITTED planes (state/planes.py) — patched
    by the same write transaction that swapped the tables, exact for this
    snapshot by construction, device-resident — and span ALL nodes
    (non-ready nodes simply never enter a ring). Without one (tests,
    direct harnesses), the legacy ready-node rebuild path is kept."""

    def __init__(self, snapshot, mirror=None):
        self.gen = getattr(snapshot, "_gen", snapshot)
        self.mirror = None
        if mirror is not None:
            view = mirror.sync(snapshot)
            if view is not None:
                self.mirror = mirror
                self.cluster = view
                self.nodes = view.nodes
                self.used0 = view.initial_used(snapshot)
                self.capacity = view.capacity
                self.usable = view.usable
                return
        nodes = [n for n in snapshot.nodes() if n.ready()]
        self.nodes = nodes
        self.cluster = ColumnarCluster.shared(snapshot, nodes)
        self.used0 = self.cluster.initial_used(snapshot).astype(np.int64)
        self.capacity = self.cluster.capacity
        self.usable = self.cluster.usable


@dataclass
class DrainPrep:
    """One eval's contribution to the fused kernel batch (all arrays are in
    the shared cluster's node-index space)."""

    eval_id: str
    priority: int
    create_index: int
    planes_list: list[GroupPlanes]
    g_index: dict[str, int]
    g_demand: np.ndarray  # i32[Gi,3]
    g_limit: np.ndarray  # i32[Gi]
    gid_real: np.ndarray  # i32[Ai]
    perm_eligible: np.ndarray  # i32[n_elig] shuffled eligible node indices
    collisions0: np.ndarray  # i32[Gi, n_real] same-job alloc counts
    by_dc: dict[str, int]
    #: the eval's wall-clock deadline (unix ns, 0 = none): the collector
    #: refuses to spend a device round on an already-expired lane
    #: (core/overload.py — the drain plane's min-deadline gate)
    deadline: int = 0


class _Parked:
    def __init__(self, prep: DrainPrep):
        self.prep = prep
        self.event = threading.Event()
        #: the eval's drain.park span context: the batch-shared build/
        #: dispatch spans nest UNDER it so park self-time in the
        #: critical path is the pure rendezvous wait, not a double-count
        #: of the fused build it temporally contains
        self.trace_ctx = None
        #: this eval's placement slice — a DEVICE array handed back at
        #: dispatch time; the consumer's np.asarray is the sync point, so
        #: host-side materialization overlaps device compute
        self.placements = None
        #: per-node usage base including every earlier eval's grants,
        #: computed on device alongside the scan (also handed back lazily)
        self.used0 = None
        self.error: Optional[BaseException] = None


class _LazySlice:
    """A view of one eval's slice of a batch-wide DEVICE array. Slicing a
    jax array per parked eval costs a dispatched device op each; this
    defers to ONE host transfer of the full array (jax caches the host
    copy on the array) sliced with plain numpy at each consumer's own
    sync point. np.asarray() works transparently via __array__. The
    optional ``on_sync`` callback fires after the first successful sync —
    the collector threads one (shared, once-only) callback through a
    batch's slices to timestamp device completion without a dedicated
    watcher thread. With ``trace_ctx``, the first sync also records a
    per-eval ``drain.materialize`` span (host-side materialization time,
    distinct from on-device compute — this slice's wait is exactly the
    part not hidden by the double-buffer overlap)."""

    __slots__ = ("arr", "sl", "on_sync", "trace_ctx")

    def __init__(self, arr, sl, on_sync=None, trace_ctx=None):
        self.arr = arr
        self.sl = sl
        self.on_sync = on_sync
        self.trace_ctx = trace_ctx

    def __array__(self, dtype=None, copy=None):
        ctx = self.trace_ctx
        t0 = time.monotonic() if ctx is not None else 0.0
        out = np.asarray(self.arr)[self.sl]
        if ctx is not None:
            self.trace_ctx = None  # first sync only; later reads are hot
            from ..trace import tracer

            # the consumer's own active span (eval.evaluate on the
            # scheduler thread) wins over the stored root ctx so the
            # materialization nests INSIDE the stage that waited for it
            # instead of overlapping it as a root sibling
            tracer.record_span(
                "drain.materialize", tracer.current() or ctx, t0,
                time.monotonic(), metric="drain.materialize",
            )
        cb = self.on_sync
        if cb is not None:
            self.on_sync = None
            try:
                cb()
            except Exception:  # timing must never fail a consumer
                logger.debug("lazy-slice sync callback failed", exc_info=True)
        if dtype is not None and out.dtype != dtype:
            out = out.astype(dtype)
        return out


#: cached jitted per-eval usage-base program (built on first drain batch;
#: lazy so oracle-only processes never touch jax)
_USED_BASES_JIT = None


def _used_bases_fn():
    global _USED_BASES_JIT
    if _USED_BASES_JIT is None:
        import functools

        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit, static_argnums=(4,))
        def bases(used0, placements, demands, eval_of, E, n_real):
            """used-before-eval-e = used0 + Σ earlier evals' granted
            demands (exclusive prefix over the eval axis) — the same
            accounting the host loop used to do after a blocking sync."""
            N, R = used0.shape
            valid = (placements >= 0) & (placements < n_real)
            rows = eval_of * N + jnp.clip(placements, 0, N - 1)
            contrib = jnp.where(valid[:, None], demands, 0)
            delta = (
                jnp.zeros((E * N, R), dtype=used0.dtype).at[rows].add(contrib)
            ).reshape(E, N, R)
            shift = jnp.concatenate(
                [jnp.zeros((1, N, R), dtype=used0.dtype),
                 jnp.cumsum(delta, axis=0)[:-1]]
            )
            return used0[None, :, :] + shift

        _USED_BASES_JIT = bases
    return _USED_BASES_JIT


from .batch_sched import _bucket  # one padding-bucket policy for all kernels
from .columnar import R_COLS


class KernelBatchCollector:
    """Rendezvous for the evals of one drain batch.

    Each eval's scheduler thread either ``submit()``s its prepared inputs
    (blocking until the fused kernel returns its placement slice) or
    ``leave()``s (fallback path / no placements / error). The last thread to
    arrive runs the combined kernel for everyone.
    """

    def __init__(self, shared: SharedCluster, expected: int, timeout: float = 60.0,
                 pad_evals: int = 0):
        self.shared = shared
        self.timeout = timeout
        self._expected = expected
        #: stable padding floor (the worker's configured drain size): fused
        #: batches of varying occupancy then share ONE compiled shape
        #: instead of recompiling per batch-size bucket
        self.pad_evals = max(pad_evals, expected)
        self._lock = threading.Lock()
        # nta: ignore[unbounded-cache] WHY: the collector is scoped to
        # one fused drain batch; both containers die with it
        self._parked: list[_Parked] = []
        # nta: ignore[unbounded-cache] WHY: batch-scoped, see above
        self._consumed: set[str] = set()
        self.invocations = 0
        #: shared per-node NetworkIndexes: every eval in the batch assigns
        #: dynamic ports through the same map (+lock) so siblings can't
        #: double-book a port on a node before either plan commits
        self.net_indexes: dict = {}
        self.net_lock = threading.Lock()

    # ------------------------------------------------------------------
    def consumed(self, eval_id: str) -> bool:
        with self._lock:
            return eval_id in self._consumed

    def leave(self, eval_id: str):
        """An eval is not participating (fallback, no-op plan, or error).
        Idempotent per eval — the scheduler's fallback path and the worker's
        finally-guard may both call it."""
        with self._lock:
            if eval_id in self._consumed:
                return
            self._consumed.add(eval_id)
            self._expected -= 1
            batch = self._take_batch_locked()
        self._run_batch(batch)

    def submit(self, prep: DrainPrep) -> tuple[np.ndarray, np.ndarray]:
        """Park this eval's inputs; returns (placements slice, usage base
        including all earlier evals' grants)."""
        from ..trace import tracer

        park = _Parked(prep)
        # opened BEFORE parking: the last-arriving thread runs the fused
        # build inside _run_batch below, and the build/dispatch spans it
        # records need this context as their parent. Closed in the
        # finally — the rendezvous wait (submit → dispatch wake), with
        # the batch-shared stages nested inside it
        park_span = tracer.start_span("drain.park")
        park.trace_ctx = park_span.ctx() or tracer.ctx_for_eval(
            prep.eval_id
        )
        with self._lock:
            self._consumed.add(prep.eval_id)
            self._parked.append(park)
            batch = self._take_batch_locked()
        try:
            self._run_batch(batch)
            arrived = park.event.wait(self.timeout)
        finally:
            park_span.end()
        if not arrived:
            raise RuntimeError("drain kernel batch timed out")
        if park.error is not None:
            raise park.error
        return park.placements, park.used0

    # ------------------------------------------------------------------
    def _take_batch_locked(self) -> Optional[list]:
        """Detach the complete batch under the lock; the caller runs it
        AFTER releasing. The fused build + device dispatch used to run
        inside the collector lock, so a sibling eval's ``leave()``
        (worker finally-guard) or ``consumed()`` probe serialized behind
        an entire kernel invocation — the analyzer's
        lock-held-blocking-call finding this refactor burned down."""
        if len(self._parked) < self._expected or not self._parked:
            return None
        parked, self._parked = self._parked, []
        self._expected = 0
        return parked

    def _run_batch(self, parked: Optional[list]):
        if not parked:
            return
        # the batch min-deadline gate (core/overload.py): lanes whose
        # deadline passed while they rendezvoused are refused BEFORE the
        # fused build and the device round — their threads wake with
        # DeadlineExceeded (the worker turns that into a terminal
        # deadline_exceeded eval outcome), and if every lane expired the
        # batch pays no device dispatch at all
        now = time.time_ns()
        expired = [
            p for p in parked if p.prep.deadline and now >= p.prep.deadline
        ]
        if expired:
            from .. import metrics
            from ..core.overload import DeadlineExceeded

            metrics.incr("overload.deadline_exceeded.drain", len(expired))
            for p in expired:
                p.error = DeadlineExceeded(
                    "drain lane refused: deadline exceeded before device "
                    "dispatch",
                    where="drain",
                )
                p.event.set()
            parked = [p for p in parked if p.error is None]
            if not parked:
                return
        # deterministic sequencing regardless of thread arrival order:
        # highest priority first, then submission order (the broker's
        # dequeue ordering), so capacity threads through the fused scan the
        # way the serialized applier would commit
        parked.sort(
            key=lambda p: (-p.prep.priority, p.prep.create_index, p.prep.eval_id)
        )
        try:
            self._run(parked)
        except BaseException as e:  # propagate to every parked thread
            logger.exception("drain kernel batch failed")
            for p in parked:
                p.error = e
        finally:
            for p in parked:
                p.event.set()

    # ------------------------------------------------------------------
    def _run(self, parked: list[_Parked]):
        import jax.numpy as jnp

        from ..trace import tracer
        from .kernel import (
            BatchArgs,
            BatchState,
            compile_cache_size,
            plan_batch,
        )

        #: per-eval trace contexts: the fused batch's stages (build,
        #: dispatch) are SHARED wall time, recorded into every
        #: participating eval's tree under its drain.park span (so park
        #: self-time stays the pure rendezvous wait); device compute —
        #: which outlives the park — attaches to the eval root
        trace_ctxs = [p.trace_ctx for p in parked]
        root_ctxs = [
            tracer.ctx_for_eval(p.prep.eval_id) for p in parked
        ]
        from . import shard as _shard
        from . import wavefront as _wavefront

        t0 = time.monotonic()
        shared = self.shared
        n_real = len(shared.nodes)
        # mesh-sharded node axis (tpu/shard.py): gated by cluster size so
        # toy drains never pay a collective; N rounds to a mesh multiple
        # so every shard holds equal rows
        mesh = _shard.active_mesh(n_real)
        N = _shard.node_bucket(n_real, mesh)
        # padding floors keyed to the configured drain size: partial batches
        # reuse the full batch's compiled shape (shape churn was costing a
        # fresh XLA compile per batch)
        E = _bucket(max(len(parked), self.pad_evals))
        G = _bucket(
            max(
                sum(len(p.prep.planes_list) for p in parked),
                self.pad_evals,
            )
        )
        A_real = sum(len(p.prep.gid_real) for p in parked)
        A = _bucket(max(A_real, self.pad_evals * 4))
        V = _bucket(
            max(
                max(
                    (len(pl.counts0) for p in parked for pl in p.prep.planes_list
                     if pl.counts0 is not None),
                    default=1,
                ),
                8,
            )
        )

        # Device-resident state plane (mirror path): capacity/usable were
        # device_put once per node-axis epoch and the used plane arrives
        # via dirty-row scatter updates — no O(N) host→device transfer per
        # batch. Fallback (no mirror / stale gen): pad + upload this
        # batch's host arrays.
        cap_in = usable_in = used_in = None
        if shared.mirror is not None:
            ds = shared.mirror.device_state(N, shared.gen, mesh=mesh)
            if ds is not None:
                cap_in, usable_in, used_in = ds
        if used_in is None:
            capacity = np.zeros((N, R_COLS), dtype=np.int32)
            capacity[:n_real] = shared.capacity
            usable = np.ones((N, 2), dtype=np.float32)
            usable[:n_real] = shared.usable
            used0 = np.full((N, R_COLS), 2**30, dtype=np.int32)
            used0[:n_real] = shared.used0
            cap_in, usable_in, used_in = capacity, usable, used0
            # over the paging budget the mirror REFUSES a resident plane
            # by design; this batch pays a transient upload instead, and
            # the counter keeps the devprof h2d bytes explainable
            from . import paging as _paging

            if _paging.should_page(N, R_COLS):
                from .. import metrics

                metrics.incr("tpu.drain_paged_fallback")

        feasible = np.zeros((G, N), dtype=bool)
        affinity = np.zeros((G, N), dtype=np.float32)
        affinity_present = np.zeros((G, N), dtype=bool)
        group_count = np.ones(G, dtype=np.int32)
        group_eval = np.full(G, E - 1, dtype=np.int32)
        node_value = np.full((G, N), -1, dtype=np.int32)
        spread_desired = np.full((G, V), -1.0, dtype=np.float32)
        spread_implicit = np.full(G, -1.0, dtype=np.float32)
        spread_weight_frac = np.zeros(G, dtype=np.float32)
        spread_even = np.zeros(G, dtype=bool)
        spread_active = np.zeros(G, dtype=bool)
        counts0 = np.zeros((G, V), dtype=np.int32)
        present0 = np.zeros((G, V), dtype=bool)
        collisions0 = np.zeros((G, N), dtype=np.int32)
        perm = np.tile(np.arange(N, dtype=np.int32), (E, 1))
        ring = np.zeros(E, dtype=np.int32)

        demands = np.zeros((A, R_COLS), dtype=np.int32)
        groups = np.zeros(A, dtype=np.int32)
        limits = np.zeros(A, dtype=np.int32)
        valid = np.zeros(A, dtype=bool)

        g_off = 0
        a_off = 0
        slices = []  # (park, a_start, a_len)
        for e, park in enumerate(parked):
            prep = park.prep
            n_elig = len(prep.perm_eligible)
            # boolean-mask complement (setdiff1d sorts; ~10x slower here)
            elig_mask = np.ones(N, dtype=bool)
            elig_mask[prep.perm_eligible] = False
            rest = np.flatnonzero(elig_mask).astype(np.int32)
            perm[e] = np.concatenate([prep.perm_eligible, rest])
            ring[e] = n_elig
            for gi, planes in enumerate(prep.planes_list):
                g = g_off + gi
                feasible[g, :n_real] = planes.feasible
                affinity[g, :n_real] = planes.affinity
                affinity_present[g, :n_real] = planes.affinity_present
                group_count[g] = planes.count
                group_eval[g] = e
                collisions0[g, :n_real] = prep.collisions0[gi]
                if planes.node_value is not None:
                    node_value[g, :n_real] = planes.node_value
                    nv = len(planes.counts0)
                    counts0[g, :nv] = planes.counts0
                    present0[g, :nv] = planes.present0
                    spread_desired[g, : len(planes.desired)] = planes.desired
                    spread_implicit[g] = planes.implicit
                    spread_weight_frac[g] = planes.weight_frac
                    spread_even[g] = planes.even
                    spread_active[g] = True
            a_len = len(prep.gid_real)
            demands[a_off : a_off + a_len] = prep.g_demand[prep.gid_real]
            groups[a_off : a_off + a_len] = prep.gid_real + g_off
            limits[a_off : a_off + a_len] = prep.g_limit[prep.gid_real]
            valid[a_off : a_off + a_len] = True
            slices.append((park, a_off, a_len))
            g_off += len(prep.planes_list)
            a_off += a_len

        args = BatchArgs(
            capacity=cap_in,
            usable=usable_in,
            feasible=feasible,
            affinity=affinity,
            affinity_present=affinity_present,
            group_count=group_count,
            group_eval=group_eval,
            node_value=node_value,
            spread_desired=spread_desired,
            spread_implicit=spread_implicit,
            spread_weight_frac=spread_weight_frac,
            spread_even=spread_even,
            spread_active=spread_active,
            perm=perm,
            ring=ring,
            demands=demands,
            groups=groups,
            limits=limits,
            valid=valid,
        )
        init = BatchState(
            used=used_in,
            collisions=collisions0,
            spread_counts=counts0,
            spread_present=present0,
            offset=np.zeros(E, dtype=np.int32),
        )
        if mesh is not None:
            # place every input with its PartitionSpec (shard.put): the
            # mirror's planes are already sharded (device_put is then a
            # no-op ref), host planes upload partitioned, and the small
            # tables replicate explicitly — one layout source with the
            # warmup prewarm, so a fused batch never pays a recompile
            aspec, sspec = _shard.batch_specs()
            args = _shard.put(args, aspec, mesh)
            init = _shard.put(init, sspec, mesh)
        else:
            from ..debug import devprof as _devprof_put

            # the single-chip upload path: leaves go up via jnp.asarray
            # without passing the counted wrapper — count the tree here
            # so the h2d ledger covers both flavors
            _devprof_put.count_tree_h2d((args, init))
            args = BatchArgs(*[jnp.asarray(a) for a in args])
            init = BatchState(*[jnp.asarray(s) for s in init])
        t_build = time.monotonic()
        cache_before = compile_cache_size()
        # n_valid: the devprof round counter charges the fused scan's
        # rounds against the REAL placements asked for, not the padded
        # lane count (rounds_per_placement ≈ A/A_real ≥ 1.0 today)
        wf_rounds = None
        if _wavefront.enabled():
            _, placements, wf_rounds = _wavefront.plan_batch_wavefront(
                args, init, n_real, n_valid=A_real,
                n_shards=_shard.mesh_size(mesh),
            )
        else:
            _, placements = plan_batch(args, init, n_real, n_valid=A_real)

        # per-eval usage bases computed ON DEVICE in the same dispatch
        # wave (double-buffering: the parked threads wake NOW, at dispatch
        # — their host-side materialization, and the next batch's group
        # assembly, overlap this batch's device compute; each consumer's
        # np.asarray is its sync point)
        eval_of = group_eval[groups]
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from ..debug import devprof as _devprof

            rep = NamedSharding(mesh, P())
            eval_of_d = _devprof.device_put(eval_of, rep)
            n_real_d = _devprof.device_put(np.int32(n_real), rep)
        else:
            eval_of_d = jnp.asarray(eval_of)
            n_real_d = jnp.int32(n_real)
        bases = _used_bases_fn()(
            init.used,
            placements,
            args.demands,
            eval_of_d,
            E,
            n_real_d,
        )
        # dispatch→first-consumer-sync wall clock (an UPPER BOUND on
        # device time: the first consumer's host-side template/id prep
        # rides in front of its sync — still the outlier detector wanted,
        # recompiles and chip contention dominate it — without a watcher
        # thread per batch)
        from .. import metrics

        t_disp = time.monotonic()
        cache_after = compile_cache_size()
        recompiled = (
            cache_before >= 0 and cache_after > cache_before
        )
        # device-aware span set, per participating eval: host build →
        # async dispatch → on-device compute (stamped at the existing
        # materialization sync points — no added syncs on the hot path).
        # A dispatch that grew the jit cache paid an XLA trace+compile in
        # its window: flagged, with the padded shapes in the tags, so the
        # 51200-vs-50176 off-bucket class is visible per trace instead of
        # inferred from bench outlier splits (shapes already round
        # through the one _bucket policy; the flag catches the misses)
        dispatch_tags = {
            "batch_evals": len(parked),
            "padded": f"E{E}xG{G}xA{A}xN{N}xV{V}",
            "mirror": shared.mirror is not None,
        }
        if wf_rounds is None:
            # the device-plane cost of this dispatch (devprof): the
            # exact scan runs one collective round per alloc lane, so a
            # trace reader sees the convoy size span-locally. The
            # wavefront's round count is a device scalar unknown at
            # dispatch time — it lands MEASURED on the device_compute
            # span at the first consumer sync instead, so the mesh
            # rounds-per-placement stats are never biased by a guess.
            dispatch_tags["collective_rounds"] = A
            dispatch_tags["placements"] = A_real
        else:
            dispatch_tags["planner"] = "wavefront"
        if mesh is not None:
            # shard topology on the dispatch span: an operator reading a
            # trace can tell a sharded dispatch (and its mesh width) from
            # a single-chip one without cross-referencing config
            dispatch_tags.update(_shard.shard_tags(mesh))
        from ..debug import devprof as _devprof_mod

        # executable cost from the compile ledger (flops / bytes /
        # collective census totals) — empty when devprof is off or the
        # program never recorded a compile in this process
        dispatch_tags.update(_devprof_mod.dispatch_tags(
            "wavefront" if wf_rounds is not None else "exact"
        ))
        if recompiled:
            dispatch_tags["jit_cache_delta"] = cache_after - cache_before
        for ctx in trace_ctxs:
            tracer.record_span(
                "drain.build", ctx, t0, t_build,
                tags={"batch_evals": len(parked)},
            )
            tracer.record_span(
                "drain.kernel_dispatch", ctx, t_build, t_disp,
                tags=dispatch_tags,
                flags=("recompile",) if recompiled else (),
            )

        fired = []
        fire_lock = threading.Lock()
        t_dispatch = t_build

        def record_kernel():
            with fire_lock:
                if fired:
                    return
                fired.append(True)
            now = time.monotonic()
            dt = now - t_dispatch
            LAST_DRAIN_STATS["kernel_s"] = dt
            metrics.sample("drain.batch_kernel", dt)
            # the first consumer sync materializes the batch-wide
            # placement + usage-base arrays host-side exactly once (jax
            # caches the host copy; every _LazySlice shares it) — THE
            # drain path's d2h transfer, counted at the moment it happens
            from ..debug import devprof as _dp

            _dp.count_d2h(
                getattr(placements, "nbytes", 0)
                + getattr(bases, "nbytes", 0),
                calls=2,
            )
            device_tags = {"batch_evals": len(root_ctxs)}
            device_tags.update(_dp.dispatch_tags(
                "wavefront" if wf_rounds is not None else "exact"
            ))
            if mesh is not None:
                device_tags.update(_shard.shard_tags(mesh))
                if wf_rounds is not None:
                    # the program has executed by this sync, so the
                    # device round scalar is free to read — the span
                    # carries MEASURED rounds and the critical-path
                    # convoy verdict sees rpp ≪ 1 on wavefront runs
                    try:
                        device_tags["collective_rounds"] = int(wf_rounds)
                    except Exception:
                        device_tags["collective_rounds"] = A
                else:
                    device_tags["collective_rounds"] = A
                device_tags["placements"] = A_real
            for ctx in root_ctxs:
                tracer.record_span(
                    "drain.device_compute", ctx, t_disp, now,
                    tags=device_tags,
                )

        for e, (park, a_start, a_len) in enumerate(slices):
            park.placements = _LazySlice(
                placements, slice(a_start, a_start + a_len),
                on_sync=record_kernel,
                trace_ctx=tracer.ctx_for_eval(park.prep.eval_id),
            )
            park.used0 = _LazySlice(bases, e, on_sync=record_kernel)

        self.invocations += 1
        DRAIN_COUNTERS["batches"] += 1
        DRAIN_COUNTERS["evals"] += len(parked)
        LAST_DRAIN_STATS.update(
            n_evals=len(parked),
            n_allocs=A_real,
            n_nodes=n_real,
            build_s=t_build - t0,
            mirror=shared.mirror is not None,
            padded=(E, G, A, N, V),
            shards=_shard.mesh_size(mesh),
        )
        metrics.sample("drain.batch_build", t_build - t0)
