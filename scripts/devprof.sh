#!/usr/bin/env bash
# Device-plane observability walkthrough on an 8-virtual-device CPU
# mesh (OBSERVABILITY.md "The device plane"): a sharded, traced
# planning pass drives the devprof instruments end-to-end, then prints
#
#   - the compile ledger (per-executable cost + HLO collective census),
#   - collective_rounds_per_placement (ROADMAP item 2's knee),
#   - the critical-path verdict — on a sharded run where device
#     dispatch dominates, it names the cross-shard collective convoy,
#   - a trailing DEVPROF_SUMMARY line (greppable, like BENCH_SUMMARY).
#
# Knobs: DEVPROF_DEVICES (8), DEVPROF_NODES (2048), DEVPROF_ALLOCS
# (4096). Real-TPU boxes: drop the XLA_FLAGS/JAX_PLATFORMS overrides.
set -euo pipefail
cd "$(dirname "$0")/.."

DEVICES="${DEVPROF_DEVICES:-8}"
export XLA_FLAGS="${XLA_FLAGS:---xla_force_host_platform_device_count=${DEVICES}}"
export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
export NOMAD_TPU_COMPILE_CACHE="${NOMAD_TPU_COMPILE_CACHE:-off}"
export NOMAD_TPU_SHARD=1
export NOMAD_TPU_SHARD_MIN_NODES="${NOMAD_TPU_SHARD_MIN_NODES:-512}"
export BENCH_NODES="${DEVPROF_NODES:-2048}"
export BENCH_ALLOCS="${DEVPROF_ALLOCS:-4096}"
export DEVPROF_DEVICES_N="${DEVICES}"

python - <<'EOF'
import json
import os

import bench
from nomad_tpu.debug import devprof
from nomad_tpu.state import StateStore
from nomad_tpu.tpu import batch_sched, shard
from nomad_tpu.trace import attribute, tracer

mesh = shard.configure(int(os.environ["DEVPROF_DEVICES_N"]))
assert mesh is not None, "mesh did not come up (device count?)"

state = StateStore()
state.upsert_nodes(1, bench.build_nodes(bench.N_NODES))
job = bench.build_job(bench.N_ALLOCS, spread=True)
state.upsert_job(2, job)

# pass 1 — the runs planner (the spread headline path): its fill runs
# already batch placements per round, so rounds/placement lands well
# under 1.0 — the counter REFUTES the per-placement hypothesis for this
# planner, with data
bench.run_once(state, job)  # warm: compiles land in the ledger
elapsed_runs, _ = bench.run_once(state, job)

# pass 2 — the exact sequential scan (the fused-drain semantics, where
# the hypothesis lives): one collective round per alloc lane. Traced,
# so the dispatch spans carry the shard topology + round tags and the
# critical-path verdict can name the convoy.
batch_sched.EXACT_ONLY = True
try:
    bench.run_once(state, job)  # warm the exact-scan mesh layout
    tracer.reset()
    # a root finished through the eval lifecycle path so the trace is
    # RETAINED (tracer.root's lexically-scoped spans stay open-ended;
    # retention is what attribute() reads)
    root = tracer.start_root("devprof.sh")
    with tracer.activate(root.ctx()):
        elapsed, placed = bench.run_once(state, job)
    tracer.finish_root(root)
finally:
    batch_sched.EXACT_ONLY = False

report = attribute(tracer.store.records())
snap = devprof.snapshot()
summ = snap["summary"]

print(devprof.format_report(snap))
print()
print(f"runs-planner pass: {elapsed_runs:.3f}s (rounds batch via fill runs)")
print(f"traced exact-scan pass: {elapsed:.3f}s, {len(placed)} placements")
print(f"critical-path verdict: {report['verdict']}")
print(f"mesh spans: {json.dumps(report['mesh'])}")
print(
    "DEVPROF_SUMMARY "
    f"devices={mesh.devices.size} "
    f"nodes={bench.N_NODES} allocs={bench.N_ALLOCS} "
    f"collective_rounds={summ['collective_rounds']} "
    f"collective_rounds_per_placement={summ['collective_rounds_per_placement']} "
    f"compile_s_total={summ['compile_s_total']} "
    f"h2d_mb={summ['h2d_mb']} d2h_mb={summ['d2h_mb']} "
    f"census_collective_ops={summ['census_collective_ops']} "
    f"convoy_named={int('collective convoy' in report['verdict'])}"
)
EOF
