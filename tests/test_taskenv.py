"""Task environment + prestart hook pipeline
(ref client/taskenv/env.go, task_runner_hooks.go:48-118,
artifact_hook.go, template_hook.go, dispatch_hook.go)."""

import base64
import os
import time

import pytest

import nomad_tpu.mock as mock
from nomad_tpu.client import hooks, taskenv
from nomad_tpu.client.hooks import HookError
from nomad_tpu.structs.model import (
    DispatchPayloadConfig,
    TaskArtifact,
    Template,
)


def make_alloc():
    alloc = mock.alloc()
    return alloc


class TestTaskEnv:
    def test_nomad_variables(self):
        alloc = make_alloc()
        node = mock.node()
        task = alloc.job.task_groups[0].tasks[0]
        env = taskenv.build_env(alloc, task, node, "/t/web", "/t/alloc")
        assert env["NOMAD_ALLOC_ID"] == alloc.id
        assert env["NOMAD_TASK_NAME"] == task.name
        assert env["NOMAD_GROUP_NAME"] == alloc.task_group
        assert env["NOMAD_TASK_DIR"] == "/t/web/local"
        assert env["NOMAD_ALLOC_DIR"] == "/t/alloc"
        assert env["NOMAD_CPU_LIMIT"] == str(task.resources.cpu)
        assert env["NOMAD_ALLOC_INDEX"] == "0"

    def test_meta_and_ports(self):
        alloc = make_alloc()
        node = mock.node()
        task = alloc.job.task_groups[0].tasks[0]
        task.meta = {"owner": "me"}
        env = taskenv.build_env(alloc, task, node, "/t/web", "/t/alloc")
        assert env["NOMAD_META_OWNER"] == "me"
        # mock alloc carries an allocated port for 'web'
        port_keys = [k for k in env if k.startswith("NOMAD_ADDR_")]
        assert port_keys, "allocated ports become NOMAD_ADDR_* vars"

    def test_interpolation(self):
        node = mock.node()
        node.attributes["rack"] = "r9"
        node.meta["zone"] = "z1"
        env = {"NOMAD_TASK_DIR": "/td/local", "FOO": "bar"}
        assert (
            taskenv.interpolate("${NOMAD_TASK_DIR}/x ${env.FOO}", env, node)
            == "/td/local/x bar"
        )
        assert taskenv.interpolate("${attr.rack}", env, node) == "r9"
        assert taskenv.interpolate("${meta.zone}", env, node) == "z1"
        assert taskenv.interpolate("${node.datacenter}", env, node) == node.datacenter
        assert taskenv.interpolate(
            {"cmd": ["${env.FOO}", 7]}, env, node
        ) == {"cmd": ["bar", 7]}


class TestHooks:
    def test_artifact_file_copy_and_template(self, tmp_path):
        src = tmp_path / "payload.bin"
        src.write_text("artifact-data")
        task_dir = tmp_path / "task"
        alloc_dir = tmp_path / "alloc"
        alloc = make_alloc()
        task = alloc.job.task_groups[0].tasks[0]
        task.artifacts = [TaskArtifact(getter_source=f"file://{src}")]
        task.templates = [
            Template(
                embedded_tmpl="job=${NOMAD_JOB_ID} dc=${node.datacenter}",
                dest_path="local/config.txt",
            )
        ]
        node = mock.node()
        prepared, env = hooks.run_prestart(
            alloc, task, node, str(task_dir), str(alloc_dir)
        )
        assert (task_dir / "local" / "payload.bin").read_text() == "artifact-data"
        rendered = (task_dir / "local" / "config.txt").read_text()
        assert rendered == f"job={alloc.job_id} dc={node.datacenter}"
        assert (alloc_dir / "data").is_dir()
        assert prepared.env["NOMAD_ALLOC_ID"] == alloc.id

    def test_artifact_http_tarball_unpacks(self, tmp_path):
        """go-getter auto-unpack: an http tar.gz artifact extracts into
        the destination and the archive itself is removed."""
        import http.server
        import tarfile
        import threading

        payload = tmp_path / "inner.txt"
        payload.write_text("packed-content")
        archive = tmp_path / "bundle.tar.gz"
        with tarfile.open(archive, "w:gz") as tf:
            tf.add(payload, arcname="inner.txt")

        class Quiet(http.server.SimpleHTTPRequestHandler):
            def __init__(self, *a, **kw):
                super().__init__(*a, directory=str(tmp_path), **kw)

            def log_message(self, *a):
                pass

        httpd = http.server.HTTPServer(("127.0.0.1", 0), Quiet)
        port = httpd.server_address[1]
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        try:
            alloc = make_alloc()
            task = alloc.job.task_groups[0].tasks[0]
            task.artifacts = [
                TaskArtifact(
                    getter_source=f"http://127.0.0.1:{port}/bundle.tar.gz"
                )
            ]
            task.templates = []
            task_dir = tmp_path / "task-http"
            hooks.run_prestart(
                alloc, task, mock.node(), str(task_dir), str(tmp_path / "a")
            )
            assert (
                task_dir / "local" / "inner.txt"
            ).read_text() == "packed-content"
            assert not (task_dir / "local" / "bundle.tar.gz").exists()
        finally:
            httpd.shutdown()

    def test_artifact_git_clone(self, tmp_path):
        import subprocess

        repo = tmp_path / "upstream"
        repo.mkdir()
        (repo / "README.md").write_text("cloned-ok")
        for cmd in (
            ["git", "init", "-q"],
            ["git", "add", "."],
            ["git", "-c", "user.email=t@t", "-c", "user.name=t",
             "commit", "-q", "-m", "init"],
        ):
            subprocess.run(cmd, cwd=repo, check=True)

        alloc = make_alloc()
        task = alloc.job.task_groups[0].tasks[0]
        task.artifacts = [TaskArtifact(getter_source=f"git::file://{repo}")]
        task.templates = []
        task_dir = tmp_path / "task-git"
        hooks.run_prestart(
            alloc, task, mock.node(), str(task_dir), str(tmp_path / "b")
        )
        assert (
            task_dir / "local" / "upstream" / "README.md"
        ).read_text() == "cloned-ok"

    def test_artifact_escape_rejected(self, tmp_path):
        alloc = make_alloc()
        task = alloc.job.task_groups[0].tasks[0]
        task.artifacts = [
            TaskArtifact(getter_source="/etc/hostname", relative_dest="../../out")
        ]
        with pytest.raises(HookError):
            hooks.run_prestart(
                alloc, task, mock.node(), str(tmp_path / "t"), str(tmp_path / "a")
            )

    def test_missing_artifact_fails(self, tmp_path):
        alloc = make_alloc()
        task = alloc.job.task_groups[0].tasks[0]
        task.artifacts = [TaskArtifact(getter_source="/does/not/exist")]
        with pytest.raises(HookError):
            hooks.run_prestart(
                alloc, task, mock.node(), str(tmp_path / "t"), str(tmp_path / "a")
            )

    def test_dispatch_payload_written(self, tmp_path):
        alloc = make_alloc()
        task = alloc.job.task_groups[0].tasks[0]
        task.dispatch_payload = DispatchPayloadConfig(file="input.dat")
        alloc.job.payload = base64.b64encode(b"dispatched").decode()
        hooks.run_prestart(
            alloc, task, mock.node(), str(tmp_path / "t"), str(tmp_path / "a")
        )
        assert (tmp_path / "t" / "local" / "input.dat").read_bytes() == b"dispatched"

    def test_config_interpolation(self, tmp_path):
        alloc = make_alloc()
        task = alloc.job.task_groups[0].tasks[0]
        task.config = {"command": "/bin/echo", "args": ["${NOMAD_ALLOC_ID}"]}
        prepared, _ = hooks.run_prestart(
            alloc, task, mock.node(), str(tmp_path / "t"), str(tmp_path / "a")
        )
        assert prepared.config["args"] == [alloc.id]


class TestEndToEnd:
    def test_task_sees_nomad_env_and_artifact(self, tmp_path):
        """A raw_exec task reads its NOMAD_* env and a fetched artifact."""
        from nomad_tpu.client.client import Client
        from nomad_tpu.core.server import Server
        from nomad_tpu.raft import InmemTransport, RaftConfig

        artifact = tmp_path / "seed.txt"
        artifact.write_text("seeded")

        cfg = {
            "seed": 42,
            "heartbeat_ttl": 600.0,
            "raft": {
                "node_id": "s0",
                "address": "raft0",
                "voters": {"s0": "raft0"},
                "transport": InmemTransport(),
                "config": RaftConfig(
                    heartbeat_interval=0.02,
                    election_timeout_min=0.05,
                    election_timeout_max=0.10,
                ),
            },
        }
        server = Server(cfg)
        server.start(num_workers=1, wait_for_leader=5.0)
        client = Client(server, data_dir=str(tmp_path / "client"))
        client.start()
        try:
            job = mock.batch_job()
            tg = job.task_groups[0]
            tg.count = 1
            task = tg.tasks[0]
            task.driver = "raw_exec"
            task.config = {
                "command": "/bin/sh",
                "args": [
                    "-c",
                    'echo "$NOMAD_ALLOC_ID" > out; cat "$NOMAD_TASK_DIR/seed.txt" >> out',
                ],
            }
            task.artifacts = [TaskArtifact(getter_source=f"file://{artifact}")]
            task.resources.networks = []
            server.job_register(job)

            deadline = time.monotonic() + 20
            while time.monotonic() < deadline:
                allocs = server.state.allocs_by_job(job.namespace, job.id)
                if allocs and allocs[0].client_status == "complete":
                    break
                time.sleep(0.05)
            (alloc,) = server.state.allocs_by_job(job.namespace, job.id)
            assert alloc.client_status == "complete"
            out = (
                tmp_path / "client" / "allocs" / alloc.id / "web" / "out"
            ).read_text()
            assert alloc.id in out and "seeded" in out
        finally:
            client.stop()
            server.stop()
