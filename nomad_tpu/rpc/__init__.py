"""msgpack-RPC transport layer (ref nomad/rpc.go + helper/pool/).

The reference multiplexes everything over one TCP listener with first-byte
protocol selection (rpc.go:170-223: RpcNomad / RpcRaft / RpcMultiplex /
RpcStreaming), msgpack-encoded frames, connection pooling, and
follower→leader + region→region forwarding. This package provides the
same: `RpcServer` (listener + endpoint registry + protocol select),
`ConnPool` (persistent pooled client connections), `TcpRaftTransport`
(raft protocol riding the same listener), and `ServerProxy` (the typed
client surface the node agent and CLI use — the api/ package analog).
"""

from .client import ConnPool, RpcError, ServerProxy  # noqa: F401
from .server import RpcServer  # noqa: F401
from .raft_transport import TcpRaftTransport  # noqa: F401
