"""Command-line interface (ref command/commands.go — the ~90-command mitchellh
CLI tree; the operationally-core subset is implemented here, one subcommand
family per reference command file)."""

from __future__ import annotations

import argparse
import json
import signal
import sys
import time

from ..api.client import APIError, ApiClient

EXAMPLE_JOB = """\
job "example" {
  datacenters = ["dc1"]
  type = "service"

  group "cache" {
    count = 1

    restart {
      attempts = 2
      interval = "30m"
      delay    = "15s"
      mode     = "fail"
    }

    task "redis" {
      driver = "mock_driver"

      config {
        run_for = "3600"
      }

      resources {
        cpu    = 500
        memory = 256
      }
    }
  }
}
"""


def _client(args) -> ApiClient:
    import os as os_mod

    return ApiClient(
        address=args.address,
        namespace=getattr(args, "namespace", "default"),
        token=getattr(args, "token", None)
        or os_mod.environ.get("NOMAD_TOKEN", ""),
    )


def _wait_for_signals(cleanup):
    stop = []
    signal.signal(signal.SIGINT, lambda *a: stop.append(1))
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    try:
        while not stop:
            time.sleep(0.2)
    finally:
        cleanup()
    return 0


def _run_networked_server(args, config: dict):
    """One real cluster member per process (ref command/agent server mode;
    the forked-binary e2e harness spawns three of these)."""
    from ..agent import ServerAgent
    from ..api.http import HTTPServer
    from ..config import server_config_from_agent

    server_stanza = config.get("server", {}) or {}
    name = config.get("name", "server-1")
    server_cfg = server_config_from_agent(config)
    agent = ServerAgent(
        name,
        bind=config.get("bind_addr", "127.0.0.1"),
        port=int(server_stanza.get("rpc_port", 0)),
        data_dir=(config.get("data_dir") or None),
        config=server_cfg,
    )
    voters = {str(k): str(v) for k, v in server_stanza["voters"].items()}
    agent.start(
        voters=voters,
        num_workers=int(server_stanza.get("num_schedulers", 2)),
    )
    port = args.port if args.port is not None else int(
        config.get("ports", {}).get("http", 4646)
    )
    http = HTTPServer(agent.server, host=args.bind, port=port)
    http.start()
    from ..client.consul_sync import syncer_from_config
    from ..metrics import configure_telemetry

    telemetry = configure_telemetry(config)
    consul_sync = syncer_from_config(config, agent.server.state.snapshot)
    print(
        f"==> nomad-tpu server {name} started: http {http.address} "
        f"rpc {agent.address}", flush=True,
    )

    def cleanup():
        print("==> shutting down", flush=True)
        if consul_sync is not None:
            consul_sync.stop()
        if telemetry is not None:
            telemetry.stop()
        http.stop()
        agent.stop()

    return _wait_for_signals(cleanup)


def _run_networked_client(args, config: dict):
    """A node agent connected to remote servers over RPC (ref command/agent
    client mode)."""
    from ..agent import ClientAgent, apply_client_config

    client_stanza = config.get("client", {}) or {}
    servers = [str(s) for s in client_stanza.get("servers", [])]
    agent = ClientAgent(
        servers,
        data_dir=(config.get("data_dir") or None),
        bind=config.get("bind_addr", "127.0.0.1"),
    )

    # reuse the stanza plumbing (host volumes, meta, plugins, vault)
    class _Shim:
        clients = [agent.client]

    apply_client_config(_Shim, config)
    agent.start()
    print(
        f"==> nomad-tpu client started: node {agent.node.id[:8]} "
        f"servers {servers}", flush=True,
    )

    def cleanup():
        print("==> shutting down", flush=True)
        agent.stop()

    return _wait_for_signals(cleanup)


def cmd_agent(args):
    """ref command/agent/command.go: -dev mode, or HCL config files with
    merge semantics and SIGHUP log-level reload."""
    from ..agent import DevAgent
    from ..api.http import HTTPServer
    from ..config import (
        apply_log_level,
        load_agent_config,
        server_config_from_agent,
    )

    config_paths = list(args.config or [])
    if not args.dev and not config_paths:
        print("provide -dev or -config <file>", file=sys.stderr)
        return 1

    config = load_agent_config(config_paths)
    apply_log_level(config)

    # networked modes (the forked-binary topology of testutil/server.go:
    # each `nomad agent` process is one real cluster member):
    #   server { enabled, rpc_port, voters { name = "host:port" } }
    #   client { enabled, servers = ["host:port", ...] }  (no local server)
    server_stanza = config.get("server", {}) or {}
    client_stanza = config.get("client", {}) or {}
    if not args.dev and server_stanza.get("voters"):
        return _run_networked_server(args, config)
    if (
        not args.dev
        and not server_stanza.get("enabled")
        and client_stanza.get("enabled")
        and client_stanza.get("servers")
    ):
        return _run_networked_client(args, config)

    server_cfg = server_config_from_agent(config)
    server_cfg["name"] = config.get("name", "server-1")
    # agents prewarm the planner shape ladder by default (first-eval
    # latency; server.prewarm_kernels=false in the HCL config disables)
    server_cfg.setdefault(
        "prewarm_kernels",
        bool(config.get("server", {}).get("prewarm_kernels", True)),
    )

    num_clients = args.clients
    if (
        not args.dev
        and config_paths
        and not config.get("client", {}).get("enabled", False)
    ):
        num_clients = 0
    agent = DevAgent(
        num_clients=num_clients,
        server_config=server_cfg,
        num_workers=int(config.get("server", {}).get("num_schedulers", 2)),
    )
    from ..agent import apply_client_config

    apply_client_config(agent, config)
    agent.start()
    port = args.port if args.port is not None else int(
        config.get("ports", {}).get("http", 4646)
    )
    http = HTTPServer(agent.server, host=args.bind, port=port, agent=agent)
    http.start()
    from ..client.consul_sync import syncer_from_config
    from ..metrics import configure_telemetry

    telemetry = configure_telemetry(config)
    consul_sync = syncer_from_config(config, agent.server.state.snapshot)
    print(f"==> nomad-tpu agent started: {http.address} "
          f"(region {agent.server.region!r})")
    print(f"    clients: {[c.node.id[:8] for c in agent.clients]}")

    stop = []

    def _reload(*_a):
        # SIGHUP: re-read config files, apply the reloadable subset
        try:
            level = apply_log_level(load_agent_config(config_paths))
            print(f"==> config reloaded (log_level={level})")
        except Exception as e:
            print(f"==> config reload failed: {e}", file=sys.stderr)

    signal.signal(signal.SIGINT, lambda *a: stop.append(1))
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    signal.signal(signal.SIGHUP, _reload)
    try:
        while not stop:
            time.sleep(0.2)
    finally:
        print("==> shutting down")
        if consul_sync is not None:
            consul_sync.stop()
        if telemetry is not None:
            telemetry.stop()
        http.stop()
        agent.stop()
    return 0


def cmd_job_init(args):
    path = args.filename or "example.nomad"
    with open(path, "w") as f:
        f.write(EXAMPLE_JOB)
    print(f"Example job file written to {path}")
    return 0


def cmd_job_run(args):
    from ..jobspec import parse_job

    with open(args.jobfile) as f:
        job = parse_job(f.read())
    client = _client(args)
    resp = client.register_job(job.to_dict())
    eval_id = resp.get("EvalID", "")
    if not eval_id:
        # periodic/parameterized jobs register without a direct evaluation
        kind = "periodic" if job.is_periodic() else "parameterized"
        print(f"==> Registered {kind} job {job.id!r} (no evaluation created)")
        return 0
    print(f"==> Evaluation {eval_id[:8]} created")
    if args.detach:
        return 0
    deadline = time.time() + 30
    while time.time() < deadline:
        ev = client.evaluation(eval_id)
        if ev["status"] in ("complete", "failed", "canceled"):
            print(f"==> Evaluation status: {ev['status']}")
            if ev.get("failed_tg_allocs"):
                for tg, metrics in ev["failed_tg_allocs"].items():
                    print(f"    group {tg}: failed to place "
                          f"({metrics.get('nodes_filtered', 0)} filtered, "
                          f"{metrics.get('nodes_exhausted', 0)} exhausted)")
                return 2
            return 0
        time.sleep(0.2)
    print("==> Timed out waiting for evaluation")
    return 1


def _render_field_diff(d, indent):
    mark = {"Added": "+", "Deleted": "-", "Edited": "~"}.get(d["Type"], " ")
    if d["Type"] == "Edited":
        print(f"{indent}{mark} {d['Name']}: {d['Old']!r} => {d['New']!r}")
    elif d["Type"] == "Added":
        print(f"{indent}{mark} {d['Name']}: {d['New']!r}")
    else:
        print(f"{indent}{mark} {d['Name']}: {d['Old']!r}")


def _render_object_diff(d, indent="  "):
    mark = {"Added": "+", "Deleted": "-", "Edited": "~"}.get(d["Type"], " ")
    print(f"{indent}{mark} {d['Name']}")
    for fd in d.get("Fields", []):
        _render_field_diff(fd, indent + "  ")
    for od in d.get("Objects", []):
        _render_object_diff(od, indent + "  ")


def cmd_job_plan(args):
    """Dry-run a job: structural diff + annotated placement decisions,
    nothing committed (ref command/job_plan.go)."""
    from ..jobspec import parse_job

    with open(args.jobfile) as f:
        job = parse_job(f.read())
    client = _client(args)
    resp = client.plan_job(job.to_dict(), diff=not args.no_diff)

    diff = resp.get("Diff")
    if diff:
        print(f"==> Job: {job.id!r} ({diff['Type']})")
        for fd in diff.get("Fields", []):
            _render_field_diff(fd, "  ")
        for od in diff.get("Objects", []):
            _render_object_diff(od)
        for tg in diff.get("TaskGroups", []):
            mark = {"Added": "+", "Deleted": "-", "Edited": "~"}.get(tg["Type"], " ")
            print(f"{mark} Task Group: {tg['Name']!r}")
            for fd in tg.get("Fields", []):
                _render_field_diff(fd, "    ")
            for od in tg.get("Objects", []):
                _render_object_diff(od, "    ")
            for td in tg.get("Tasks", []):
                tmark = {"Added": "+", "Deleted": "-", "Edited": "~"}.get(td["Type"], " ")
                print(f"    {tmark} Task: {td['Name']!r}")
                for fd in td.get("Fields", []):
                    _render_field_diff(fd, "      ")
                for od in td.get("Objects", []):
                    _render_object_diff(od, "      ")

    annotations = resp.get("Annotations") or {}
    updates = annotations.get("desired_tg_updates") or {}
    if updates:
        print("==> Scheduler dry-run:")
        for tg, u in updates.items():
            parts = []
            for key, label in (
                ("place", "place"),
                ("stop", "stop"),
                ("in_place_update", "in-place update"),
                ("destructive_update", "destructive update"),
                ("migrate", "migrate"),
                ("canary", "canary"),
                ("ignore", "ignore"),
            ):
                if u.get(key):
                    parts.append(f"{u[key]} {label}")
            detail = ", ".join(parts) if parts else "no changes"
            print(f"    group {tg!r}: {detail}")
    failed = resp.get("FailedTGAllocs") or {}
    for tg, metrics in failed.items():
        print(f"    group {tg!r}: WOULD FAIL to place "
              f"({metrics.get('nodes_filtered', 0)} filtered, "
              f"{metrics.get('nodes_exhausted', 0)} exhausted)")
    print(f"==> Job Modify Index: {resp.get('JobModifyIndex', 0)}")
    return 2 if failed else 0


def cmd_job_status(args):
    client = _client(args)
    if not args.job_id:
        jobs = client.jobs()
        if not jobs:
            print("No running jobs")
            return 0
        print(f"{'ID':<30} {'Type':<10} {'Priority':<9} Status")
        for j in jobs:
            print(f"{j['ID']:<30} {j['Type']:<10} {j['Priority']:<9} {j['Status']}")
        return 0
    job = client.job(args.job_id)
    print(f"ID            = {job['id']}")
    print(f"Name          = {job['name']}")
    print(f"Type          = {job['type']}")
    print(f"Priority      = {job['priority']}")
    print(f"Datacenters   = {','.join(job['datacenters'])}")
    print(f"Status        = {job['status']}")
    try:
        summary = client.job_summary(args.job_id)
        print("\nSummary")
        print(f"{'Task Group':<15} {'Queued':<7} {'Starting':<9} {'Running':<8} "
              f"{'Failed':<7} {'Complete':<9} Lost")
        for tg, s in summary["summary"].items():
            print(f"{tg:<15} {s['queued']:<7} {s['starting']:<9} {s['running']:<8} "
                  f"{s['failed']:<7} {s['complete']:<9} {s['lost']}")
    except APIError:
        pass
    # placement failures from the newest eval (ref job_status.go's
    # "Placement Failure" section via the monitor's metric formatter)
    try:
        evals = client.job_evaluations(args.job_id)
        newest_failed = next(
            (
                e
                for e in sorted(
                    evals,
                    key=lambda e: e.get("modify_index", 0),
                    reverse=True,
                )
                if e.get("failed_tg_allocs")
            ),
            None,
        )
        if newest_failed is not None:
            print("\nPlacement Failure")
            _render_alloc_metrics(newest_failed["failed_tg_allocs"])
    except APIError:
        pass
    allocs = client.job_allocations(args.job_id)
    if allocs:
        print("\nAllocations")
        print(f"{'ID':<10} {'Node ID':<10} {'Task Group':<12} {'Desired':<8} Status")
        for a in allocs:
            print(f"{a['ID'][:8]:<10} {a['NodeID'][:8]:<10} "
                  f"{a['TaskGroup']:<12} {a['DesiredStatus']:<8} {a['ClientStatus']}")
    return 0


def cmd_job_stop(args):
    client = _client(args)
    resp = client.deregister_job(args.job_id, purge=args.purge)
    print(f"==> Evaluation {resp.get('EvalID', '')[:8]} created")
    return 0


def cmd_node_status(args):
    client = _client(args)
    if not args.node_id:
        nodes = client.nodes()
        print(f"{'ID':<10} {'DC':<6} {'Name':<16} {'Class':<18} "
              f"{'Drain':<6} {'Eligibility':<12} Status")
        for n in nodes:
            print(f"{n['ID'][:8]:<10} {n['Datacenter']:<6} {n['Name'][:15]:<16} "
                  f"{(n['NodeClass'] or '<none>'):<18} {str(n['Drain']).lower():<6} "
                  f"{n['SchedulingEligibility']:<12} {n['Status']}")
        return 0
    node = client.node(args.node_id)
    print(f"ID          = {node['id']}")
    print(f"Name        = {node['name']}")
    print(f"Datacenter  = {node['datacenter']}")
    print(f"Class       = {node['node_class'] or '<none>'}")
    print(f"Status      = {node['status']}")
    print(f"Drain       = {node['drain']}")
    res = node.get("node_resources") or {}
    if res:
        print(f"Resources   = cpu {res['cpu']['cpu_shares']} MHz, "
              f"mem {res['memory']['memory_mb']} MB, "
              f"disk {res['disk']['disk_mb']} MB")
    allocs = client.node_allocations(node["id"])
    if allocs:
        print("\nAllocations")
        for a in allocs:
            print(f"  {a['ID'][:8]} {a['JobID'][:24]:<26} "
                  f"{a['DesiredStatus']:<8} {a['ClientStatus']}")
    if getattr(args, "stats", False):
        try:
            stats = client.client_stats(node["id"])
        except Exception as e:
            print(f"\nHost Stats  = unavailable ({e})")
            return 0
        mem = stats.get("memory", {})
        disk = stats.get("disk", {})
        cpu = stats.get("cpu", {})
        print("\nHost Stats")
        print(f"  CPU    = {cpu.get('total_percent', 0):.1f}% busy")
        print(f"  Memory = {mem.get('used', 0) // (1 << 20)} MiB used / "
              f"{mem.get('total', 0) // (1 << 20)} MiB")
        print(f"  Disk   = {disk.get('used_percent', 0):.1f}% of "
              f"{disk.get('size', 0) // (1 << 30)} GiB")
        print(f"  Uptime = {stats.get('uptime_s', 0):.0f}s")
    return 0


def cmd_node_drain(args):
    client = _client(args)
    enable = not args.disable
    deadline_ns = 0
    if enable and args.deadline:
        from ..jobspec.hcl import parse_duration as hcl_duration

        deadline_ns = hcl_duration(args.deadline)
    client.drain_node(
        args.node_id,
        enable,
        deadline_ns=deadline_ns,
        ignore_system_jobs=args.ignore_system,
    )
    print(f"Node {args.node_id[:8]} drain {'enabled' if enable else 'disabled'}")
    return 0


def cmd_node_eligibility(args):
    client = _client(args)
    elig = "ineligible" if args.elig_disable else "eligible"
    client.put(f"/v1/node/{args.node_id}/eligibility", body={"Eligibility": elig})
    print(f"Node {args.node_id[:8]} marked {elig}")
    return 0


def cmd_alloc_logs(args):
    """ref command/alloc_logs.go (poll-follow on the offset cursor)"""
    client = _client(args)
    kind = "stderr" if args.stderr else "stdout"
    params = {"task": args.task, "type": kind}
    resp = client.get(f"/v1/client/fs/logs/{args.alloc_id}", **params)[0]
    print(resp.get("Data", ""), end="")
    if args.follow:
        offset = resp.get("Offset", 0)
        try:
            while True:
                time.sleep(1.0)
                resp = client.get(
                    f"/v1/client/fs/logs/{args.alloc_id}",
                    **params,
                    offset=offset,
                )[0]
                if resp.get("Data"):
                    print(resp["Data"], end="", flush=True)
                    offset = resp.get("Offset", offset)
        except KeyboardInterrupt:
            return 0
    return 0


def cmd_alloc_fs(args):
    """ref command/alloc_fs.go: ls a directory, cat a file"""
    client = _client(args)
    path = args.path or "/"
    try:
        entries = client.get(f"/v1/client/fs/ls/{args.alloc_id}", path=path)[0]
        for entry in entries:
            kind = "d" if entry["IsDir"] else "-"
            print(f"{kind} {entry['Size']:>10}  {entry['Name']}")
        return 0
    except APIError:
        resp = client.get(f"/v1/client/fs/cat/{args.alloc_id}", path=path)[0]
        print(resp.get("Data", ""), end="")
        return 0


def cmd_alloc_exec(args):
    """ref command/alloc_exec.go: interactive streaming session with
    -i/-t (websocket → server → client → driver exec-in-context), or the
    legacy one-shot captured exec without."""
    client = _client(args)
    # resolve a short alloc-id prefix to the full id (ref command/meta:
    # every alloc command accepts prefixes)
    alloc_id = client.allocation(args.alloc_id)["id"]
    if not (args.interactive or args.tty):
        resp = client.put(
            f"/v1/client/exec/{alloc_id}",
            body={"Task": args.task, "Cmd": args.cmd},
        )[0]
        if resp.get("Stdout"):
            print(resp["Stdout"], end="")
        if resp.get("Stderr"):
            import sys

            print(resp["Stderr"], end="", file=sys.stderr)
        return resp.get("ExitCode", 0)

    import os
    import sys
    import threading

    session = client.alloc_exec_session(
        alloc_id, args.task, args.cmd, tty=args.tty
    )
    exit_code = [0]
    done = threading.Event()

    raw = False
    if args.tty and sys.stdin.isatty():
        import termios
        import tty as tty_mod

        saved = termios.tcgetattr(sys.stdin.fileno())
        tty_mod.setraw(sys.stdin.fileno())
        raw = True
        try:
            cols, rows = os.get_terminal_size()
            session.resize(rows, cols)
        except OSError:
            pass

    def stdin_pump():
        try:
            while not done.is_set():
                data = os.read(sys.stdin.fileno(), 4096)
                if not data:
                    session.close_stdin()
                    return
                session.send_stdin(data)
        except (OSError, ValueError):
            pass

    t = threading.Thread(
        target=stdin_pump, daemon=True, name="cli-exec-stdin-pump"
    )
    t.start()
    try:
        while True:
            frame = session.recv_frame(timeout=3600)
            if frame is None:
                break
            if frame.get("stdout"):
                sys.stdout.buffer.write(frame["stdout"])
                sys.stdout.flush()
            if frame.get("stderr"):
                sys.stderr.buffer.write(frame["stderr"])
                sys.stderr.flush()
            if frame.get("error"):
                print(frame["error"], file=sys.stderr)
                exit_code[0] = 1
                break
            if frame.get("exited"):
                exit_code[0] = int(frame.get("exit_code", 0))
                break
    finally:
        done.set()
        session.close()
        if raw:
            termios.tcsetattr(
                sys.stdin.fileno(), termios.TCSADRAIN, saved
            )
    return exit_code[0]


def cmd_alloc_status(args):
    client = _client(args)
    alloc = client.allocation(args.alloc_id)
    print(f"ID            = {alloc['id']}")
    print(f"Name          = {alloc['name']}")
    print(f"Node ID       = {alloc['node_id'][:8]}")
    print(f"Job ID        = {alloc['job_id']}")
    print(f"Desired       = {alloc['desired_status']}")
    print(f"Client Status = {alloc['client_status']}")
    states = alloc.get("task_states") or {}
    for task, st in states.items():
        print(f"\nTask \"{task}\": {st['state']}"
              + (" (failed)" if st.get("failed") else ""))
        print(f"  Restarts = {st.get('restarts', 0)}")
    if getattr(args, "stats", False):
        try:
            stats = client.alloc_stats(alloc["id"])
        except Exception as e:
            print(f"\nResource Usage = unavailable ({e})")
            return 0
        print("\nResource Usage")
        for task, usage in sorted(stats.get("tasks", {}).items()):
            print(f"  {task}: cpu {usage.get('cpu_time_s', 0)}s, "
                  f"rss {usage.get('rss_bytes', 0) // (1 << 20)} MiB, "
                  f"pids {usage.get('pids', 0)}")
    return 0


def cmd_alloc_stop(args):
    client = _client(args)
    out = client.alloc_stop(args.alloc_id)
    print(f"Stop requested; eval {out['EvalID']}")
    return 0


def cmd_alloc_restart(args):
    client = _client(args)
    out = client.alloc_restart(args.alloc_id, task=args.task or "")
    print(f"Restarted tasks: {', '.join(out['tasks'])}")
    return 0


def cmd_alloc_signal(args):
    client = _client(args)
    out = client.alloc_signal(
        args.alloc_id, signal=args.signal, task=args.task or ""
    )
    print(f"Signaled tasks: {', '.join(out['tasks'])}")
    return 0


def cmd_eval_status(args):
    client = _client(args)
    ev = client.evaluation(args.eval_id)
    print(f"ID            = {ev['id']}")
    print(f"Type          = {ev['type']}")
    print(f"TriggeredBy   = {ev['triggered_by']}")
    print(f"Job ID        = {ev['job_id']}")
    print(f"Status        = {ev['status']}")
    if ev.get("status_description"):
        print(f"Description   = {ev['status_description']}")
    queued = {k: v for k, v in (ev.get("queued_allocations") or {}).items() if v}
    if queued:
        print(f"Queued        = {queued}")
    _render_alloc_metrics(ev.get("failed_tg_allocs") or {})
    return 0


def _render_alloc_metrics(failed_tg_allocs: dict):
    """Placement failure breakdown (ref command/monitor.go
    formatAllocMetrics: the signature debugging surface)."""
    for tg, metric in failed_tg_allocs.items():
        print(f"\nTask Group {tg!r} (failed to place"
              + (f", {metric['coalesced_failures']} coalesced" if metric.get("coalesced_failures") else "")
              + "):")
        print(f"  Nodes evaluated = {metric.get('nodes_evaluated', 0)}")
        print(f"  Nodes filtered  = {metric.get('nodes_filtered', 0)}")
        print(f"  Nodes exhausted = {metric.get('nodes_exhausted', 0)}")
        for constraint, n in (metric.get("constraint_filtered") or {}).items():
            print(f"  Constraint {constraint!r} filtered {n} nodes")
        for dim, n in (metric.get("dimension_exhausted") or {}).items():
            print(f"  Resource {dim!r} exhausted on {n} nodes")
        for cls, n in (metric.get("class_filtered") or {}).items():
            print(f"  Class {cls!r} filtered {n} nodes")


def cmd_deployment_list(args):
    client = _client(args)
    rows = client.deployments()
    print(f"{'ID':<10} {'Job ID':<24} {'Status':<12} Description")
    for d in rows:
        print(
            f"{d['id'][:8]:<10} {d['job_id'][:22]:<24} "
            f"{d['status']:<12} {d['status_description']}"
        )
    return 0


def cmd_deployment_status(args):
    client = _client(args)
    d = client.deployment(args.deployment_id)
    print(f"ID          = {d['id']}")
    print(f"Job ID      = {d['job_id']}")
    print(f"Job Version = {d['job_version']}")
    print(f"Status      = {d['status']}")
    print(f"Description = {d['status_description']}")
    print()
    print("Deployed")
    print(f"{'Task Group':<12} {'Desired':>8} {'Placed':>8} {'Healthy':>8} {'Unhealthy':>10}")
    for name, s in d.get("task_groups", {}).items():
        print(
            f"{name:<12} {s['desired_total']:>8} {s['placed_allocs']:>8} "
            f"{s['healthy_allocs']:>8} {s['unhealthy_allocs']:>10}"
        )
    return 0


def cmd_deployment_promote(args):
    _client(args).deployment_promote(args.deployment_id, groups=args.group or None)
    print(f"Deployment {args.deployment_id[:8]} promoted")
    return 0


def cmd_deployment_fail(args):
    _client(args).deployment_fail(args.deployment_id)
    print(f"Deployment {args.deployment_id[:8]} marked as failed")
    return 0


def cmd_deployment_pause(args):
    _client(args).deployment_pause(args.deployment_id, not args.resume)
    verb = "resumed" if args.resume else "paused"
    print(f"Deployment {args.deployment_id[:8]} {verb}")
    return 0


def cmd_job_revert(args):
    out = _client(args).job_revert(args.job_id, args.version)
    print(f"Job {args.job_id} reverted to version {args.version}")
    if out.get("EvalID"):
        print(f"Evaluation ID: {out['EvalID']}")
    return 0


def cmd_job_dispatch(args):
    client = _client(args)
    payload = ""
    if args.payload_file:
        with open(args.payload_file) as f:
            payload = f.read()
    meta = {}
    for kv in args.meta or []:
        if "=" not in kv:
            print(f"Error: -meta expects KEY=VALUE, got {kv!r}", file=sys.stderr)
            return 1
        k, v = kv.split("=", 1)
        meta[k] = v
    out = client.job_dispatch(args.job_id, payload=payload, meta=meta)
    print(f"Dispatched Job ID = {out['DispatchedJobID']}")
    print(f"Evaluation ID     = {out['EvalID']}")
    return 0


def cmd_job_periodic_force(args):
    out = _client(args).job_periodic_force(args.job_id)
    print(f"Forced periodic launch: {out['DispatchedJobID']}")
    return 0


def cmd_job_history(args):
    client = _client(args)
    versions = client.job_versions(args.job_id)
    by_version = {v["version"]: v for v in versions}
    for v in versions:
        print(f"Version     = {v['version']}")
        print(f"Stable      = {v['stable']}")
        print(f"Submit Date = {v.get('submit_time', 0)}")
        if getattr(args, "diffs", False) and (v["version"] - 1) in by_version:
            # ref command/job_history.go -p: structural diff vs previous
            from ..structs.diff import job_diff
            from ..structs.model import Job

            prev = Job.from_dict(by_version[v["version"] - 1])
            cur = Job.from_dict(v)
            diff = job_diff(prev, cur)
            if diff and diff.get("Type") != "None":
                print("Diff        =")
                for fd in diff.get("Fields", []):
                    _render_field_diff(fd, "  ")
                for tg in diff.get("TaskGroups", []):
                    if tg["Type"] == "None":
                        continue
                    print(f"  ~ Task Group {tg['Name']!r}")
                    for fd in tg.get("Fields", []):
                        _render_field_diff(fd, "    ")
                    for td in tg.get("Tasks", []):
                        if td["Type"] == "None":
                            continue
                        print(f"    ~ Task {td['Name']!r}")
                        for fd in td.get("Fields", []):
                            _render_field_diff(fd, "      ")
        print()
    return 0


def cmd_job_deployments(args):
    client = _client(args)
    rows = client.job_deployments(args.job_id)
    print(f"{'ID':<10} {'Job Version':>12} {'Status':<12} Description")
    for d in rows:
        print(
            f"{d['id'][:8]:<10} {d['job_version']:>12} {d['status']:<12} "
            f"{d['status_description']}"
        )
    return 0


def cmd_job_validate(args):
    from ..jobspec import parse_job

    client = _client(args)
    with open(args.path) as f:
        job = parse_job(f.read())
    out = client.validate_job(job.to_dict())
    if out.get("ValidationErrors"):
        print("Job validation errors:")
        for e in out["ValidationErrors"]:
            print(f"  * {e}")
        return 1
    print("Job validation successful")
    return 0


def cmd_job_inspect(args):
    import json as json_mod

    client = _client(args)
    print(json_mod.dumps(client.job(args.job_id), indent=2, sort_keys=True))
    return 0


def cmd_job_eval(args):
    client = _client(args)
    out = client.job_evaluate(args.job_id, force_reschedule=args.force_reschedule)
    print(f"Created eval {out['EvalID']}")
    return 0


def cmd_eval_list(args):
    client = _client(args)
    evals = client.evaluations()
    print(f"{'ID':<10} {'Priority':<9} {'Triggered By':<18} {'Job ID':<28} Status")
    for e in evals:
        print(f"{e['id'][:8]:<10} {e['priority']:<9} {e['triggered_by']:<18} "
              f"{e['job_id'][:26]:<28} {e['status']}")
    return 0


def cmd_acl(args):
    client = _client(args)
    sub = args.acl_cmd
    if sub == "bootstrap":
        t = client.acl_bootstrap()
        print(f"Accessor ID = {t['AccessorID']}")
        print(f"Secret ID   = {t['SecretID']}")
        print(f"Type        = {t['Type']}")
        return 0
    if sub == "policy-apply":
        with open(args.path) as f:
            rules = f.read()
        client.acl_put_policy(args.name, rules, description=args.description or "")
        print(f"Successfully wrote {args.name!r} ACL policy")
        return 0
    if sub == "policy-list":
        for p in client.acl_policies():
            print(f"{p['Name']:<24} {p.get('Description', '')}")
        return 0
    if sub == "policy-info":
        p = client.acl_policy(args.name)
        print(f"Name        = {p['Name']}")
        print(f"Description = {p['Description']}")
        print("Rules:")
        print(p["Rules"])
        return 0
    if sub == "policy-delete":
        client.acl_delete_policy(args.name)
        print(f"Deleted policy {args.name!r}")
        return 0
    if sub == "token-create":
        t = client.acl_create_token(
            name=args.name or "",
            type=args.type,
            policies=args.policy or [],
            global_token=args.global_token,
        )
        print(f"Accessor ID = {t['AccessorID']}")
        print(f"Secret ID   = {t['SecretID']}")
        print(f"Type        = {t['Type']}")
        print(f"Policies    = {t['Policies']}")
        return 0
    if sub == "token-list":
        for t in client.acl_tokens():
            print(f"{t['AccessorID'][:8]:<10} {t['Type']:<12} "
                  f"{t['Name'] or '<none>':<24} {','.join(t['Policies'])}")
        return 0
    if sub == "token-info":
        t = client.acl_token(args.accessor)
        print(f"Accessor ID = {t['AccessorID']}")
        print(f"Name        = {t['Name']}")
        print(f"Type        = {t['Type']}")
        print(f"Policies    = {t['Policies']}")
        return 0
    if sub == "token-self":
        t = client.acl_token_self()
        print(f"Accessor ID = {t['AccessorID']}")
        print(f"Type        = {t['Type']}")
        print(f"Policies    = {t['Policies']}")
        return 0
    if sub == "token-delete":
        client.acl_delete_token(args.accessor)
        print(f"Deleted token {args.accessor[:8]}")
        return 0
    print(f"unknown acl subcommand: {sub}")
    return 1


def cmd_operator_raft_list(args):
    client = _client(args)
    cfg = client.raft_configuration()
    print(f"{'Node':<16} {'ID':<16} {'Address':<24} {'Leader':<7} Voter")
    for s in cfg["Servers"]:
        print(f"{s['Node']:<16} {s['ID']:<16} {s['Address']:<24} "
              f"{str(s['Leader']).lower():<7} {str(s['Voter']).lower()}")
    return 0


def cmd_operator_raft_remove(args):
    client = _client(args)
    client.raft_remove_peer(args.peer_id)
    print(f"Removed peer {args.peer_id}")
    return 0


def cmd_operator_autopilot_get(args):
    client = _client(args)
    for k, v in sorted(client.autopilot_configuration().items()):
        print(f"{k} = {v}")
    return 0


def cmd_operator_autopilot_set(args):
    client = _client(args)
    overrides = {}
    if args.cleanup_dead_servers is not None:
        overrides["cleanup_dead_servers"] = args.cleanup_dead_servers == "true"
    if args.last_contact_threshold is not None:
        overrides["last_contact_threshold_s"] = float(args.last_contact_threshold)
    if args.max_trailing_logs is not None:
        overrides["max_trailing_logs"] = int(args.max_trailing_logs)
    client.autopilot_set_configuration(overrides)
    print("Configuration updated!")
    return 0


def cmd_operator_debug(args):
    """Capture a debug bundle from the running agent (ref `nomad
    operator debug`): profiles, flight-recorder dump, slowest traces,
    metrics, redacted config — one tarball for the support ticket.
    Requires enable_debug on the agent."""
    client = _client(args)
    output = args.output or time.strftime(
        "nomad-tpu-debug-%Y%m%d-%H%M%S.tar.gz"
    )
    data = client.debug_bundle(seconds=args.seconds, output=output)
    # print the findings headline from the bundle itself, so the
    # operator sees the verdict without unpacking anything
    try:
        import io
        import tarfile

        with tarfile.open(fileobj=io.BytesIO(data)) as tar:
            member = next(
                mem for mem in tar.getmembers()
                if mem.name.endswith("findings.json")
            )
            summary = json.loads(tar.extractfile(member).read())
        frac = summary.get("applier_block_frac")
        if frac is not None:
            print(f"applier_block_frac = {frac}")
        for row in (summary.get("top_blocked_sites") or [])[:3]:
            print(
                f"blocked {row['class']:<9} {row['site']:<40} "
                f"share={row['share']}"
            )
    except Exception:
        pass  # the bundle itself is the deliverable
    print(f"Debug bundle written to {output}")
    return 0


def cmd_operator_device(args):
    """Print the live server's device-plane numbers (`operator
    device`): compile ledger top-N, collective_rounds_per_placement —
    the ROADMAP item 2 knee as one number off a running cluster — and
    the h2d/d2h transfer totals. Reads /v1/metrics' tpu_devprof key via
    ApiClient.device_stats; -json dumps the raw payload."""
    from ..debug import devprof

    payload = _client(args).device_stats()
    if not payload:
        print("device plane dark (devprof disabled or no TPU dispatches)")
        return 0
    if args.as_json:
        print(json.dumps(payload, indent=1))
        return 0
    print(devprof.format_report(payload, top=args.top))
    return 0


def cmd_operator_keygen(args):
    from ..gossip.keyring import generate_key

    print(generate_key())
    return 0


def cmd_operator_keyring(args):
    client = _client(args)
    if args.install:
        out = client.put("/v1/agent/keyring/install", body={"Key": args.install})[0]
    elif args.use:
        out = client.put("/v1/agent/keyring/use", body={"Key": args.use})[0]
    elif args.remove:
        out = client.put("/v1/agent/keyring/remove", body={"Key": args.remove})[0]
    else:
        out = client.put("/v1/agent/keyring/list")[0]
    print(f"Primary: {out['PrimaryKey'][:12]}…")
    for k in out["Keys"]:
        print(f"  {k[:12]}…")
    return 0


def cmd_system_gc(args):
    _client(args).system_gc()
    print("System GC triggered")
    return 0


def cmd_system_reconcile(args):
    _client(args).reconcile_summaries()
    print("Job summaries reconciled")
    return 0


def cmd_server_join(args):
    client = _client(args)
    out = client.agent_join(args.address)
    print(f"Joined {out['num_joined']} servers successfully")
    return 0


def cmd_server_force_leave(args):
    client = _client(args)
    client.agent_force_leave(args.node)
    print(f"Force-leave issued for {args.node}")
    return 0


def cmd_monitor(args):
    import time as time_mod

    client = _client(args)
    index = 0
    try:
        while True:
            out = client.agent_monitor(index=index, log_level=args.log_level or "")
            for e in out["Entries"]:
                print(e["message"])
            index = out["Index"]
            if not args.follow:
                return 0
            time_mod.sleep(1.0)
    except KeyboardInterrupt:
        return 0


def cmd_event_stream(args):
    """Follow the cluster event stream (ref command/event/stream.go
    `nomad event stream`): one JSON object per line, or a compact
    human-readable line with -short."""
    import time as time_mod

    client = _client(args)
    index = args.index or 0
    delay = 1.0
    # WHY: one interactive stream, reconnect paced at human timescale —
    # a budget here would only mute the operator's terminal mid-incident
    while True:  # nta: ignore[retry-without-budget]
        try:
            stream = client.event_stream(
                topics=args.topic or None,
                index=index,
                namespace=args.namespace,
            )
        except KeyboardInterrupt:
            return 0
        except Exception as e:
            # connection refused / reset: exactly what -reconnect is for
            if not args.reconnect:
                raise
            print(
                f"stream dial failed: {e}; retrying in {delay:.0f}s",
                file=sys.stderr,
            )
            try:
                time_mod.sleep(delay)
            except KeyboardInterrupt:
                return 0
            delay = min(delay * 2, 15.0)
            continue
        delay = 1.0
        try:
            for frame in stream:
                if frame.get("Error"):
                    # resume from OUR last consumed index (exactly-once);
                    # the server's ResumeIndex is only a floor for a
                    # consumer that never received anything — resuming
                    # below it would just re-print delivered events
                    index = (
                        stream.last_index
                        or frame.get("ResumeIndex", 0)
                        or index
                    )
                    print(
                        f"stream closed: {frame['Error']} "
                        f"(resuming from index {index})",
                        file=sys.stderr,
                    )
                    break
                if frame.get("LostGap"):
                    print(
                        f"[gap] events through index {frame.get('Index', 0)} "
                        "were dropped before this subscriber read them",
                        file=sys.stderr,
                    )
                    continue
                if args.short:
                    for e in frame.get("Events", []):
                        key = e.get("Key", "")
                        print(
                            f"{e.get('Index', 0):>8}  "
                            f"{e.get('Topic', ''):<11} "
                            f"{e.get('Type', ''):<28} {key[:36]}"
                        )
                else:
                    print(json.dumps(frame))
                sys.stdout.flush()
                index = stream.last_index or index
        except KeyboardInterrupt:
            stream.close()
            return 0
        if not args.reconnect:
            return 0
        try:
            time_mod.sleep(1.0)  # never hot-loop re-dials on instant closes
        except KeyboardInterrupt:
            return 0


def cmd_trace_list(args):
    """List retained traces (OBSERVABILITY.md): newest first, or the
    slowest-N / error keeps with -slowest / -errors."""
    client = _client(args)
    out = client.traces(
        limit=args.limit, slowest=args.slowest, errors=args.errors
    )
    stats = out.get("stats", {})
    print(
        f"retained={stats.get('retained', 0)} "
        f"open={stats.get('open', 0)} "
        f"finished={stats.get('finished', 0)} "
        f"sample_rate={stats.get('sample_rate', 1.0)}"
    )
    rows = out.get("traces", [])
    if not rows:
        print("No retained traces")
        return 0
    print(f"{'Trace ID':<34} {'Root':<12} {'Duration':>10} {'Spans':>6}  Err")
    for r in rows:
        dur = r.get("duration_ms")
        print(
            f"{r['trace_id']:<34} {str(r.get('root')):<12} "
            f"{dur if dur is not None else '-':>10} "
            f"{r.get('spans', 0):>6}  {'x' if r.get('error') else ''}"
        )
    return 0


def cmd_trace_get(args):
    """One trace's span tree, indented by parent (or raw JSON)."""
    from ..trace.critical_path import build_tree

    client = _client(args)
    record = client.trace(args.trace_id)
    if args.json:
        print(json.dumps(record, indent=2))
        return 0
    print(
        f"trace {record['trace_id']}  duration="
        f"{record.get('duration_ms')}ms  spans={len(record['spans'])}  "
        f"orphans={record.get('orphans', 0)}"
    )
    roots, children = build_tree(record)
    t_base = min(
        (s.get("start") or 0.0 for s in record["spans"]), default=0.0
    )

    def show(span, depth):
        rel = ((span.get("start") or 0.0) - t_base) * 1e3
        flags = ",".join(span.get("flags") or [])
        err = span.get("error")
        line = (
            f"{'  ' * depth}{span['name']:<{max(28 - 2 * depth, 8)}} "
            f"+{rel:9.2f}ms {span.get('duration_ms', 0):>10.2f}ms"
        )
        if flags:
            line += f"  [{flags}]"
        if err:
            line += f"  ERROR: {err}"
        print(line)
        for child in children.get(span["span_id"], ()):
            show(child, depth + 1)

    for root in sorted(roots, key=lambda s: s.get("start") or 0.0):
        show(root, 0)
    return 0


def cmd_trace_critical_path(args):
    """Aggregate critical-path attribution over the retained traces —
    the per-stage blame table for the eval.e2e tail."""
    from ..trace.critical_path import format_report

    client = _client(args)
    report = client.trace_critical_path(tail=args.tail)
    if args.json:
        print(json.dumps(report, indent=2))
        return 0
    print(format_report(report))
    return 0


def cmd_status(args):
    """Generic prefix dispatch (ref command/status.go): search all
    contexts and show the best match."""
    client = _client(args)
    if not args.prefix:
        args.job_id = None
        return cmd_job_status(args)
    out = client.put(
        "/v1/search", body={"Prefix": args.prefix, "Context": "all"}
    )[0]
    found = False
    for context in ("jobs", "allocs", "nodes", "evals", "deployments"):
        ids = (out.get("matches") or {}).get(context) or []
        if ids:
            found = True
            print(f"{context}: {', '.join(ids[:10])}")
    if not found:
        print(f"No matches found for {args.prefix!r}")
    return 0


def cmd_ui(args):
    addr = args.address or "http://127.0.0.1:4646"
    print(f"Opening Nomad UI: {addr}/ui/")
    return 0


def cmd_server_members(args):
    client = _client(args)
    info = client.agent_self()
    member = info["member"]
    print(f"{'Name':<12} Status")
    print(f"{member['Name']:<12} {member['Status']}")
    return 0


def cmd_agent_info(args):
    client = _client(args)
    print(json.dumps(client.agent_self(), indent=2))
    return 0


def cmd_version(args):
    from .. import __version__

    print(f"nomad-tpu v{__version__}")
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="nomad-tpu")
    p.add_argument("-address", default=None, help="agent HTTP address")
    p.add_argument(
        "-namespace", default="default",
        help="target namespace ('*' lists all authorized namespaces)",
    )
    p.add_argument(
        "-token", default=None,
        help="ACL secret (falls back to $NOMAD_TOKEN)",
    )
    sub = p.add_subparsers(dest="command")

    agent = sub.add_parser("agent", help="run the agent")
    agent.add_argument("-dev", action="store_true")
    agent.add_argument("-bind", default="127.0.0.1")
    agent.add_argument("-port", type=int, default=None)
    agent.add_argument("-clients", type=int, default=1)
    agent.add_argument(
        "-config", action="append",
        help="HCL agent config file (repeatable; merged in order)",
    )
    agent.set_defaults(fn=cmd_agent)

    job = sub.add_parser("job", help="job commands")
    jsub = job.add_subparsers(dest="subcommand")
    jp = jsub.add_parser("plan", help="dry-run a job: diff + placements")
    jp.add_argument("jobfile")
    jp.add_argument("--no-diff", action="store_true")
    jp.set_defaults(fn=cmd_job_plan)
    jr = jsub.add_parser("run")
    jr.add_argument("jobfile")
    jr.add_argument("-detach", action="store_true")
    jr.set_defaults(fn=cmd_job_run)
    js = jsub.add_parser("status")
    js.add_argument("job_id", nargs="?")
    js.set_defaults(fn=cmd_job_status)
    jst = jsub.add_parser("stop")
    jst.add_argument("job_id")
    jst.add_argument("-purge", action="store_true")
    jst.set_defaults(fn=cmd_job_stop)
    ji = jsub.add_parser("init")
    ji.add_argument("filename", nargs="?")
    ji.set_defaults(fn=cmd_job_init)
    jdp = jsub.add_parser("dispatch")
    jdp.add_argument("job_id")
    jdp.add_argument("payload_file", nargs="?")
    jdp.add_argument("-meta", action="append", metavar="KEY=VALUE")
    jdp.set_defaults(fn=cmd_job_dispatch)
    jpf = jsub.add_parser("periodic")
    jpf_sub = jpf.add_subparsers(dest="periodic_cmd")
    jpff = jpf_sub.add_parser("force")
    jpff.add_argument("job_id")
    jpff.set_defaults(fn=cmd_job_periodic_force)
    jrv = jsub.add_parser("revert")
    jrv.add_argument("job_id")
    jrv.add_argument("version", type=int)
    jrv.set_defaults(fn=cmd_job_revert)
    jh = jsub.add_parser("history")
    jh.add_argument("-p", "--diffs", action="store_true", dest="diffs",
                    help="show structural diffs between versions")
    jh.add_argument("job_id")
    jh.set_defaults(fn=cmd_job_history)
    jd = jsub.add_parser("deployments")
    jd.add_argument("job_id")
    jd.set_defaults(fn=cmd_job_deployments)
    jv = jsub.add_parser("validate", help="validate a jobspec without running it")
    jv.add_argument("path")
    jv.set_defaults(fn=cmd_job_validate)
    jins = jsub.add_parser("inspect", help="dump the registered job as JSON")
    jins.add_argument("job_id")
    jins.set_defaults(fn=cmd_job_inspect)
    jev = jsub.add_parser("eval", help="force a fresh evaluation of a job")
    jev.add_argument("-force-reschedule", "--force-reschedule",
                     action="store_true", dest="force_reschedule")
    jev.add_argument("job_id")
    jev.set_defaults(fn=cmd_job_eval)

    node = sub.add_parser("node", help="node commands")
    nsub = node.add_subparsers(dest="subcommand")
    ns = nsub.add_parser("status")
    ns.add_argument("-stats", "--stats", action="store_true", dest="stats")
    ns.add_argument("node_id", nargs="?")
    ns.set_defaults(fn=cmd_node_status)
    nd = nsub.add_parser("drain")
    nd.add_argument("node_id")
    nd.add_argument("-disable", action="store_true")
    nd.add_argument("-deadline", default="", help='force deadline, e.g. "5m"')
    nd.add_argument("-ignore-system", dest="ignore_system", action="store_true")
    nd.set_defaults(fn=cmd_node_drain)
    ne = nsub.add_parser("eligibility")
    ne.add_argument("node_id")
    ne_group = ne.add_mutually_exclusive_group(required=True)
    ne_group.add_argument("-enable", dest="elig_enable", action="store_true")
    ne_group.add_argument("-disable", dest="elig_disable", action="store_true")
    ne.set_defaults(fn=cmd_node_eligibility)

    alloc = sub.add_parser("alloc", help="allocation commands")
    asub = alloc.add_subparsers(dest="subcommand")
    alog = asub.add_parser("logs", help="task log window (poll-follow)")
    alog.add_argument("alloc_id")
    alog.add_argument("task")
    alog.add_argument("--stderr", action="store_true")
    alog.add_argument("-f", "--follow", action="store_true")
    alog.set_defaults(fn=cmd_alloc_logs)
    afs = asub.add_parser("fs", help="browse the allocation directory")
    afs.add_argument("alloc_id")
    afs.add_argument("path", nargs="?")
    afs.set_defaults(fn=cmd_alloc_fs)
    aex = asub.add_parser(
        "exec", help="run a command in the task's execution context"
    )
    aex.add_argument("alloc_id")
    aex.add_argument("task")
    aex.add_argument("cmd", nargs="+")
    aex.add_argument(
        "-i", "--interactive", action="store_true",
        help="stream stdin to the command (websocket session)",
    )
    aex.add_argument(
        "-t", "--tty", action="store_true",
        help="allocate a pseudo-terminal (implies streaming)",
    )
    aex.set_defaults(fn=cmd_alloc_exec)
    ast = asub.add_parser("status")
    ast.add_argument("alloc_id")
    ast.add_argument("-stats", "--stats", action="store_true", dest="stats")
    ast.set_defaults(fn=cmd_alloc_status)
    astop = asub.add_parser("stop", help="stop and reschedule an allocation")
    astop.add_argument("alloc_id")
    astop.set_defaults(fn=cmd_alloc_stop)
    arst = asub.add_parser("restart", help="restart an allocation's tasks")
    arst.add_argument("alloc_id")
    arst.add_argument("task", nargs="?")
    arst.set_defaults(fn=cmd_alloc_restart)
    asig = asub.add_parser("signal", help="signal an allocation's tasks")
    asig.add_argument("-s", "--signal", default="SIGINT")
    asig.add_argument("alloc_id")
    asig.add_argument("task", nargs="?")
    asig.set_defaults(fn=cmd_alloc_signal)

    ev = sub.add_parser("eval", help="evaluation commands")
    esub = ev.add_subparsers(dest="subcommand")
    est = esub.add_parser("status")
    est.add_argument("eval_id")
    est.set_defaults(fn=cmd_eval_status)

    dep = sub.add_parser("deployment", help="deployment commands")
    dsub = dep.add_subparsers(dest="subcommand")
    dl = dsub.add_parser("list")
    dl.set_defaults(fn=cmd_deployment_list)
    dst = dsub.add_parser("status")
    dst.add_argument("deployment_id")
    dst.set_defaults(fn=cmd_deployment_status)
    dp = dsub.add_parser("promote")
    dp.add_argument("deployment_id")
    dp.add_argument("-group", action="append")
    dp.set_defaults(fn=cmd_deployment_promote)
    df = dsub.add_parser("fail")
    df.add_argument("deployment_id")
    df.set_defaults(fn=cmd_deployment_fail)
    dpa = dsub.add_parser("pause")
    dpa.add_argument("deployment_id")
    dpa.add_argument("-resume", action="store_true")
    dpa.set_defaults(fn=cmd_deployment_pause)

    server = sub.add_parser("server", help="server commands")
    ssub = server.add_subparsers(dest="subcommand")
    sm = ssub.add_parser("members")
    sm.set_defaults(fn=cmd_server_members)
    sj = ssub.add_parser("join", help="join this server to a gossip peer")
    sj.add_argument("address")
    sj.set_defaults(fn=cmd_server_join)
    sfl = ssub.add_parser("force-leave", help="force a failed server out")
    sfl.add_argument("node")
    sfl.set_defaults(fn=cmd_server_force_leave)

    ev2 = esub.add_parser("list")
    ev2.set_defaults(fn=cmd_eval_list)

    acl = sub.add_parser("acl", help="ACL policies and tokens")
    aclsub = acl.add_subparsers(dest="acl_group")
    ab = aclsub.add_parser("bootstrap")
    ab.set_defaults(fn=cmd_acl, acl_cmd="bootstrap")
    apol = aclsub.add_parser("policy")
    apolsub = apol.add_subparsers(dest="acl_policy_cmd")
    apa = apolsub.add_parser("apply")
    apa.add_argument("-description", "--description")
    apa.add_argument("name")
    apa.add_argument("path")
    apa.set_defaults(fn=cmd_acl, acl_cmd="policy-apply")
    apl = apolsub.add_parser("list")
    apl.set_defaults(fn=cmd_acl, acl_cmd="policy-list")
    api_ = apolsub.add_parser("info")
    api_.add_argument("name")
    api_.set_defaults(fn=cmd_acl, acl_cmd="policy-info")
    apd = apolsub.add_parser("delete")
    apd.add_argument("name")
    apd.set_defaults(fn=cmd_acl, acl_cmd="policy-delete")
    atok = aclsub.add_parser("token")
    atoksub = atok.add_subparsers(dest="acl_token_cmd")
    atc = atoksub.add_parser("create")
    atc.add_argument("-name", "--name")
    atc.add_argument("-type", "--type", default="client")
    atc.add_argument("-policy", "--policy", action="append")
    atc.add_argument("-global", "--global", action="store_true",
                     dest="global_token")
    atc.set_defaults(fn=cmd_acl, acl_cmd="token-create")
    atl = atoksub.add_parser("list")
    atl.set_defaults(fn=cmd_acl, acl_cmd="token-list")
    ati = atoksub.add_parser("info")
    ati.add_argument("accessor")
    ati.set_defaults(fn=cmd_acl, acl_cmd="token-info")
    ats = atoksub.add_parser("self")
    ats.set_defaults(fn=cmd_acl, acl_cmd="token-self")
    atd = atoksub.add_parser("delete")
    atd.add_argument("accessor")
    atd.set_defaults(fn=cmd_acl, acl_cmd="token-delete")

    op = sub.add_parser("operator", help="cluster operator commands")
    opsub = op.add_subparsers(dest="operator_group")
    opraft = opsub.add_parser("raft")
    opraftsub = opraft.add_subparsers(dest="raft_cmd")
    orl = opraftsub.add_parser("list-peers")
    orl.set_defaults(fn=cmd_operator_raft_list)
    orr = opraftsub.add_parser("remove-peer")
    orr.add_argument("peer_id")
    orr.set_defaults(fn=cmd_operator_raft_remove)
    odbg = opsub.add_parser(
        "debug", help="capture a debug bundle from the agent"
    )
    odbg.add_argument(
        "-seconds", type=float, default=2.0,
        help="sampling-profiler duration inside the bundle (default 2s)",
    )
    odbg.add_argument(
        "-output", default=None,
        help="tarball path (default nomad-tpu-debug-<timestamp>.tar.gz)",
    )
    odbg.set_defaults(fn=cmd_operator_debug)
    odev = opsub.add_parser(
        "device",
        help="device-plane stats: compile ledger, collective rounds, "
        "transfer totals (debug/devprof.py)",
    )
    odev.add_argument(
        "-top", type=int, default=8,
        help="compile-ledger rows to print (default 8)",
    )
    odev.add_argument(
        "-json", action="store_true", dest="as_json",
        help="dump the raw tpu_devprof payload",
    )
    odev.set_defaults(fn=cmd_operator_device)
    okg = opsub.add_parser("keygen", help="generate a gossip encryption key")
    okg.set_defaults(fn=cmd_operator_keygen)
    okr = opsub.add_parser("keyring", help="manage the gossip keyring")
    okr.add_argument("-install", "--install")
    okr.add_argument("-use", "--use")
    okr.add_argument("-remove", "--remove")
    okr.set_defaults(fn=cmd_operator_keyring)
    opap = opsub.add_parser("autopilot")
    opapsub = opap.add_subparsers(dest="autopilot_cmd")
    oag = opapsub.add_parser("get-config")
    oag.set_defaults(fn=cmd_operator_autopilot_get)
    oas = opapsub.add_parser("set-config")
    oas.add_argument("-cleanup-dead-servers", "--cleanup-dead-servers",
                     dest="cleanup_dead_servers", choices=["true", "false"])
    oas.add_argument("-last-contact-threshold", "--last-contact-threshold",
                     dest="last_contact_threshold")
    oas.add_argument("-max-trailing-logs", "--max-trailing-logs",
                     dest="max_trailing_logs")
    oas.set_defaults(fn=cmd_operator_autopilot_set)

    system = sub.add_parser("system", help="system maintenance")
    syssub = system.add_subparsers(dest="system_cmd")
    sgc = syssub.add_parser("gc")
    sgc.set_defaults(fn=cmd_system_gc)
    srec = syssub.add_parser("reconcile")
    srecsub = srec.add_subparsers(dest="reconcile_cmd")
    srs = srecsub.add_parser("summaries")
    srs.set_defaults(fn=cmd_system_reconcile)

    event = sub.add_parser("event", help="cluster event stream")
    evsub = event.add_subparsers(dest="subcommand")
    evs = evsub.add_parser(
        "stream", help="follow /v1/event/stream (NDJSON frames)"
    )
    evs.add_argument(
        "-topic", action="append",
        help='topic filter, "Topic" or "Topic:key" (repeatable; default all)',
    )
    evs.add_argument(
        "-index", type=int, default=0,
        help="resume after this raft index (exclusive)",
    )
    evs.add_argument(
        "-short", action="store_true",
        help="one compact line per event instead of raw JSON frames",
    )
    evs.add_argument(
        "-reconnect", action="store_true",
        help="auto-reconnect from the last index when the stream closes",
    )
    evs.set_defaults(fn=cmd_event_stream)

    tr = sub.add_parser("trace", help="eval span trees + critical path")
    trsub = tr.add_subparsers(dest="subcommand")
    trl = trsub.add_parser("list", help="retained traces")
    trl.add_argument("-limit", type=int, default=25)
    trl.add_argument(
        "-slowest", action="store_true", help="the slowest-N tail keep"
    )
    trl.add_argument(
        "-errors", action="store_true", help="the error/fault keep"
    )
    trl.set_defaults(fn=cmd_trace_list)
    trg = trsub.add_parser("get", help="one trace's span tree")
    trg.add_argument("trace_id")
    trg.add_argument("-json", action="store_true")
    trg.set_defaults(fn=cmd_trace_get)
    trc = trsub.add_parser(
        "critical-path",
        help="per-stage attribution of the eval.e2e tail",
    )
    trc.add_argument(
        "-tail", type=float, default=0.99,
        help="tail quantile to attribute (default 0.99)",
    )
    trc.add_argument("-json", action="store_true")
    trc.set_defaults(fn=cmd_trace_critical_path)

    mon = sub.add_parser("monitor", help="stream agent logs")
    mon.add_argument("-log-level", "--log-level", dest="log_level")
    mon.add_argument("-f", "--follow", action="store_true")
    mon.set_defaults(fn=cmd_monitor)

    st = sub.add_parser("status", help="status of any prefix (job/alloc/node/eval)")
    st.add_argument("prefix", nargs="?")
    st.set_defaults(fn=cmd_status)

    uip = sub.add_parser("ui", help="print the web UI address")
    uip.set_defaults(fn=cmd_ui)

    ai = sub.add_parser("agent-info")
    ai.set_defaults(fn=cmd_agent_info)

    ver = sub.add_parser("version")
    ver.set_defaults(fn=cmd_version)
    return p


def main(argv=None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    fn = getattr(args, "fn", None)
    if fn is None:
        parser.print_help()
        return 1
    try:
        return fn(args)
    except BrokenPipeError:
        # `nomad ... | head` closed our stdout: normal unix behavior,
        # not an error worth reporting
        try:
            sys.stdout.close()
        except Exception:
            pass
        return 0
    except APIError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    except FileNotFoundError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    except Exception as e:  # jobspec parse errors, connection refused, ...
        print(f"Error: {type(e).__name__}: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
