"""QEMU task driver (ref drivers/qemu/driver.go): boot a VM image as the
task process.

Task config:
  image_path       VM image (required)
  accelerator      kvm|tcg (default kvm when /dev/kvm exists, else tcg)
  graceful_shutdown  send ACPI powerdown via monitor before SIGKILL
  port_map         {vm_port: host_port_label} user-net hostfwd rules
  args             raw extra qemu arguments
"""

from __future__ import annotations

import os
import shutil

from ..client.driver import RawExecDriver, TaskHandle
from ..structs.model import Task

QEMU_BINARIES = (
    "qemu-system-x86_64",
    "qemu-system-aarch64",
    "qemu-kvm",
)


class QemuDriver(RawExecDriver):
    name = "qemu"

    def __init__(self, binary: str = ""):
        super().__init__()
        self._qemu = binary or next(
            (p for b in QEMU_BINARIES if (p := shutil.which(b))), None
        )
        self._version = ""
        if self._qemu:
            self._version = self._probe_version()

    def _probe_version(self) -> str:
        import subprocess

        try:
            out = subprocess.run(
                [self._qemu, "--version"],
                capture_output=True,
                text=True,
                timeout=10,
            )
            # "QEMU emulator version 6.2.0 ..."
            for tok in out.stdout.split():
                if tok[:1].isdigit():
                    return tok
        except (OSError, subprocess.TimeoutExpired):
            pass
        return ""

    def fingerprint(self) -> dict:
        detected = bool(self._qemu)
        attrs = {}
        if detected:
            attrs["driver.qemu.version"] = self._version
        return {"detected": detected, "healthy": detected, "attributes": attrs}

    def start_task(self, task: Task, task_dir: str) -> TaskHandle:
        if not self._qemu:
            raise RuntimeError("qemu not found on this node")
        cfg = task.config or {}
        image = cfg.get("image_path")
        if not image:
            raise RuntimeError("qemu requires image_path")
        mem = task.resources.memory_mb or 512
        argv = [
            self._qemu,
            "-machine",
            "type=pc,accel="
            + cfg.get(
                "accelerator",
                "kvm" if os.path.exists("/dev/kvm") else "tcg",
            ),
            "-m",
            f"{mem}M",
            "-drive",
            f"file={image}",
            "-nographic",
            "-nodefaults",
        ]
        port_map = cfg.get("port_map") or {}
        if port_map:
            # user-mode net with hostfwd per mapping (ref qemu driver's
            # port_map → hostfwd_tcp rules); host ports come from the
            # task's reserved/dynamic port labels
            ports = {}
            for net in task.resources.networks:
                for p in list(net.reserved_ports) + list(net.dynamic_ports):
                    ports[p.label] = p.value
            fwds = []
            for vm_port, label in port_map.items():
                host_port = ports.get(label)
                if host_port:
                    fwds.append(f"hostfwd=tcp::{host_port}-:{vm_port}")
            argv += ["-netdev", "user,id=user.0," + ",".join(fwds), "-device", "virtio-net,netdev=user.0"]
        argv += [str(a) for a in cfg.get("args", [])]
        return self._spawn(task, argv, task_dir or None)
