"""Reconciler-unit corpus ported from the reference
(scheduler/reconcile_test.go — cited per test). Drives AllocReconciler
directly with the Go suite's stub update functions (ignore / destructive
/ inplace), asserting the same desired-change shapes."""

import pytest

from nomad_tpu import mock
from nomad_tpu.scheduler.reconcile import AllocReconciler
from nomad_tpu.structs.model import (
    ALLOC_CLIENT_STATUS_COMPLETE,
    ALLOC_CLIENT_STATUS_FAILED,
    ALLOC_CLIENT_STATUS_RUNNING,
    ALLOC_DESIRED_STATUS_RUN,
    DeploymentTaskGroupState,
    DeploymentStatus,
    TaskState,
    UpdateStrategy,
    generate_uuid,
    now_ns,
)

MINUTE_NS = 60 * 1_000_000_000
SECOND_NS = 1_000_000_000

# the Go suite's stub update functions (reconcile_test.go:36-60)
def update_ignore(existing, new_job, new_tg):
    return True, False, None


def update_destructive(existing, new_job, new_tg):
    return False, True, None


def update_inplace(existing, new_job, new_tg):
    return False, False, existing


def service_job(count=10):
    job = mock.job()
    job.task_groups[0].count = count
    return job


def allocs_for(job, n, node_prefix="node", name_start=0):
    out = []
    for i in range(name_start, name_start + n):
        a = mock.alloc()
        a.job = job
        a.job_id = job.id
        a.namespace = job.namespace
        a.node_id = f"{node_prefix}-{i}"
        a.name = f"{job.id}.web[{i}]"
        a.client_status = ALLOC_CLIENT_STATUS_RUNNING
        out.append(a)
    return out


def reconcile(job, allocs, update_fn=update_ignore, tainted=None,
              deployment=None, batch=False):
    r = AllocReconciler(
        update_fn, batch, job.id if job else "job", job, deployment,
        allocs, tainted or {}, generate_uuid(),
    )
    return r.compute()


def assert_results(results, place=0, destructive=0, inplace=0, stop=0,
                   create_deployment=None):
    assert len(results.place) == place, f"place {len(results.place)}"
    assert len(results.destructive_update) == destructive
    assert len(results.inplace_update) == inplace
    assert len(results.stop) == stop
    if create_deployment is not None:
        assert (results.deployment is not None) == create_deployment


class TestReconcilerPlacePort:
    def test_place_no_existing(self):
        """ref TestReconciler_Place_NoExisting."""
        job = service_job()
        results = reconcile(job, [])
        assert_results(results, place=10)
        assert results.desired_tg_updates["web"].place == 10

    def test_place_existing(self):
        """ref TestReconciler_Place_Existing: 5 running → 5 more."""
        job = service_job()
        allocs = allocs_for(job, 5)
        results = reconcile(job, allocs)
        assert_results(results, place=5)
        assert results.desired_tg_updates["web"].ignore == 5

    def test_scale_down_partial(self):
        """ref TestReconciler_ScaleDown_Partial: 20 → 10 stops 10."""
        job = service_job()
        allocs = allocs_for(job, 20)
        results = reconcile(job, allocs)
        assert_results(results, stop=10)
        assert results.desired_tg_updates["web"].stop == 10

    def test_scale_down_zero(self):
        """ref TestReconciler_ScaleDown_Zero."""
        job = service_job(count=0)
        allocs = allocs_for(job, 20)
        results = reconcile(job, allocs)
        assert_results(results, stop=20)

    def test_scale_down_zero_duplicate_names(self):
        """ref TestReconciler_ScaleDown_Zero_DuplicateNames: duplicated
        name indexes still all stop."""
        job = service_job(count=0)
        allocs = []
        for i in range(20):
            a = allocs_for(job, 1, name_start=i % 2)[0]
            a.id = generate_uuid()
            a.node_id = f"node-{i}"
            allocs.append(a)
        results = reconcile(job, allocs)
        assert_results(results, stop=20)

    def test_inplace(self):
        """ref TestReconciler_Inplace: all 10 updated in place."""
        job = service_job()
        allocs = allocs_for(job, 10)
        results = reconcile(job, allocs, update_fn=update_inplace)
        assert_results(results, inplace=10)

    def test_inplace_scale_up(self):
        """ref TestReconciler_Inplace_ScaleUp: 10 inplace + 5 place."""
        job = service_job(count=15)
        allocs = allocs_for(job, 10)
        results = reconcile(job, allocs, update_fn=update_inplace)
        assert_results(results, place=5, inplace=10)

    def test_inplace_scale_down(self):
        """ref TestReconciler_Inplace_ScaleDown: 20 → 5 inplace + 15 stop."""
        job = service_job(count=5)
        allocs = allocs_for(job, 20)
        results = reconcile(job, allocs, update_fn=update_inplace)
        assert_results(results, inplace=5, stop=15)

    def test_destructive(self):
        """ref TestReconciler_Destructive: all 10 destructively updated."""
        job = service_job()
        allocs = allocs_for(job, 10)
        results = reconcile(job, allocs, update_fn=update_destructive)
        assert_results(results, destructive=10)

    def test_destructive_scale_up(self):
        """ref TestReconciler_Destructive_ScaleUp."""
        job = service_job(count=15)
        allocs = allocs_for(job, 10)
        results = reconcile(job, allocs, update_fn=update_destructive)
        assert_results(results, place=5, destructive=10)

    def test_destructive_scale_down(self):
        """ref TestReconciler_Destructive_ScaleDown: 20 → 5 destructive +
        15 stop."""
        job = service_job(count=5)
        allocs = allocs_for(job, 20)
        results = reconcile(job, allocs, update_fn=update_destructive)
        assert_results(results, destructive=5, stop=15)


class TestReconcilerTaintPort:
    def _tainted(self, allocs, n, down=True):
        tainted = {}
        for i in range(n):
            node = mock.node()
            node.id = allocs[i].node_id
            if down:
                node.status = "down"
            else:
                node.drain = True
                allocs[i].desired_transition.migrate = True
            tainted[node.id] = node
        return tainted

    def test_lost_node(self):
        """ref TestReconciler_LostNode: 2 lost → 2 stop + 2 place."""
        job = service_job()
        allocs = allocs_for(job, 10)
        tainted = self._tainted(allocs, 2, down=True)
        results = reconcile(job, allocs, tainted=tainted)
        assert_results(results, place=2, stop=2)
        upd = results.desired_tg_updates["web"]
        assert upd.ignore == 8

    def test_lost_node_scale_up(self):
        """ref TestReconciler_LostNode_ScaleUp: lost + scale 10→15."""
        job = service_job(count=15)
        allocs = allocs_for(job, 10)
        tainted = self._tainted(allocs, 2, down=True)
        results = reconcile(job, allocs, tainted=tainted)
        assert_results(results, place=7, stop=2)

    def test_lost_node_scale_down(self):
        """ref TestReconciler_LostNode_ScaleDown: 10 allocs scaling to 5
        with 2 lost — the lost ones count toward the reduction, so 5 stops
        total and no replacements."""
        job = service_job(count=5)
        allocs = allocs_for(job, 10)
        tainted = self._tainted(allocs, 2, down=True)
        results = reconcile(job, allocs, tainted=tainted)
        assert_results(results, stop=5)
        upd = results.desired_tg_updates["web"]
        assert upd.ignore == 5

    def test_drain_node(self):
        """ref TestReconciler_DrainNode: 2 draining → migrate both."""
        job = service_job()
        allocs = allocs_for(job, 10)
        tainted = self._tainted(allocs, 2, down=False)
        results = reconcile(job, allocs, tainted=tainted)
        assert_results(results, place=2, stop=2)
        upd = results.desired_tg_updates["web"]
        assert upd.migrate == 2
        # migrated placements carry previous_alloc linkage
        for p in results.place:
            assert p.previous_alloc is not None

    def test_drain_node_scale_up(self):
        """ref TestReconciler_DrainNode_ScaleUp."""
        job = service_job(count=15)
        allocs = allocs_for(job, 10)
        tainted = self._tainted(allocs, 2, down=False)
        results = reconcile(job, allocs, tainted=tainted)
        assert_results(results, place=7, stop=2)

    def test_drain_node_scale_down(self):
        """ref TestReconciler_DrainNode_ScaleDown: 20 → 5 with 3 draining;
        the drain stops count toward the scale-down."""
        job = service_job(count=5)
        allocs = allocs_for(job, 20)
        tainted = self._tainted(allocs, 3, down=False)
        results = reconcile(job, allocs, tainted=tainted)
        assert len(results.place) == 0
        assert len(results.stop) == 15


class TestReconcilerJobStatePort:
    def test_removed_tg(self):
        """ref TestReconciler_RemovedTG: allocs of a removed group stop,
        the new group fills."""
        job = service_job()
        allocs = allocs_for(job, 10)
        job = job.copy()
        job.task_groups[0].name = "web2"
        results = reconcile(job, allocs)
        assert_results(results, place=10, stop=10)

    def test_job_stopped(self):
        """ref TestReconciler_JobStopped."""
        job = service_job()
        job.stop = True
        allocs = allocs_for(job, 10)
        results = reconcile(job, allocs)
        assert_results(results, stop=10)

    def test_job_stopped_terminal_allocs(self):
        """ref TestReconciler_JobStopped_TerminalAllocs: nothing to do."""
        job = service_job()
        job.stop = True
        allocs = allocs_for(job, 10)
        for a in allocs:
            a.desired_status = "stop"
        results = reconcile(job, allocs)
        assert_results(results, stop=0)

    def test_multi_tg(self):
        """ref TestReconciler_MultiTG: second group fills independently."""
        job = service_job()
        tg2 = job.task_groups[0].copy()
        tg2.name = "web2"
        job.task_groups.append(tg2)
        allocs = allocs_for(job, 2)
        results = reconcile(job, allocs)
        assert_results(results, place=18)


class TestReconcilerDeploymentPort:
    def _deployment_job(self, canaries=0, max_parallel=4):
        job = service_job()
        job.task_groups[0].update = UpdateStrategy(
            max_parallel=max_parallel,
            canary=canaries,
            health_check="checks",
            min_healthy_time=10 * SECOND_NS,
            healthy_deadline=10 * MINUTE_NS,
        )
        return job

    def test_rolling_upgrade_destructive_creates_deployment(self):
        """ref TestReconciler_CreateDeployment_RollingUpgrade_Destructive."""
        job = self._deployment_job()
        allocs = allocs_for(job, 10)
        results = reconcile(job, allocs, update_fn=update_destructive)
        assert results.deployment is not None
        state = results.deployment.task_groups["web"]
        assert state.desired_total == 10
        assert len(results.destructive_update) == 4  # max_parallel

    def test_no_changes_no_deployment(self):
        """ref TestReconciler_DontCreateDeployment_NoChanges."""
        job = self._deployment_job()
        allocs = allocs_for(job, 10)
        results = reconcile(job, allocs, update_fn=update_ignore)
        assert results.deployment is None
        assert_results(results)

    def _active_deployment(self, job, promoted=False, status="running"):
        dep = mock.deployment()
        dep.job_id = job.id
        dep.namespace = job.namespace
        dep.job_create_index = job.create_index
        dep.job_modify_index = job.job_modify_index
        dep.status = status
        dep.task_groups["web"] = DeploymentTaskGroupState(
            promoted=promoted, desired_total=10,
        )
        return dep

    @pytest.mark.parametrize("status", ["paused", "failed"])
    def test_paused_or_failed_no_more_canaries(self, status):
        """ref TestReconciler_PausedOrFailedDeployment_NoMoreCanaries."""
        job = self._deployment_job(canaries=2)
        dep = self._active_deployment(job, status=status)
        dep.task_groups["web"].desired_canaries = 2
        allocs = allocs_for(job, 10)
        results = reconcile(
            job, allocs, update_fn=update_destructive, deployment=dep
        )
        assert len(results.place) == 0, "no canaries while paused/failed"

    @pytest.mark.parametrize("status", ["paused", "failed"])
    def test_paused_or_failed_no_more_placements(self, status):
        """ref TestReconciler_PausedOrFailedDeployment_NoMorePlacements:
        scale-up placements wait for the deployment."""
        job = self._deployment_job()
        job.task_groups[0].count = 15
        dep = self._active_deployment(job, status=status)
        allocs = allocs_for(job, 10)
        results = reconcile(
            job, allocs, update_fn=update_ignore, deployment=dep
        )
        assert len(results.place) == 0

    @pytest.mark.parametrize("status", ["paused", "failed"])
    def test_paused_or_failed_no_more_destructive(self, status):
        """ref TestReconciler_PausedOrFailedDeployment_NoMoreDestructiveUpdates."""
        job = self._deployment_job()
        dep = self._active_deployment(job, status=status)
        allocs = allocs_for(job, 10)
        results = reconcile(
            job, allocs, update_fn=update_destructive, deployment=dep
        )
        assert len(results.destructive_update) == 0

    def test_dont_reschedule_previously_rescheduled(self):
        """ref TestReconciler_DontReschedule_PreviouslyRescheduled: an
        alloc whose replacement exists (next_allocation set) isn't
        rescheduled again."""
        job = service_job(count=2)
        allocs = allocs_for(job, 2)
        now = now_ns()
        allocs[0].client_status = ALLOC_CLIENT_STATUS_FAILED
        allocs[0].task_states = {
            "web": TaskState(
                state="dead", failed=True,
                started_at=now - 3600 * SECOND_NS,
                finished_at=now - 10 * SECOND_NS,
            )
        }
        allocs[0].next_allocation = allocs[1].id
        results = reconcile(job, allocs)
        # a fresh placement fills the name, but NOT as a reschedule of the
        # already-replaced alloc
        for p in results.place:
            assert p.previous_alloc is None or p.previous_alloc.id != allocs[0].id
