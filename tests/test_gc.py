"""CoreScheduler garbage collection (ref nomad/core_sched.go:43-630,
leader.go:440 schedulePeriodic, system_endpoint.go GarbageCollect)."""

import time

import nomad_tpu.mock as mock
from nomad_tpu.core.core_sched import TimeTable
from nomad_tpu.core.server import Server
from nomad_tpu.raft import InmemTransport, RaftConfig


def make_server(config=None):
    cfg = dict(config or {})
    cfg.setdefault("seed", 42)
    cfg.setdefault("heartbeat_ttl", 600.0)
    cfg["raft"] = {
        "node_id": "s0",
        "address": "raft0",
        "voters": {"s0": "raft0"},
        "transport": InmemTransport(),
        "config": RaftConfig(
            heartbeat_interval=0.02,
            election_timeout_min=0.05,
            election_timeout_max=0.10,
        ),
    }
    s = Server(cfg)
    s.start(num_workers=1, wait_for_leader=5.0)
    return s


def wait_until(fn, timeout=15.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


def run_job(server, count=2):
    job = mock.job()
    job.task_groups[0].count = count
    job.task_groups[0].tasks[0].resources.networks = []
    eval_id = server.job_register(job)
    wait_until(
        lambda: (server.state.eval_by_id(eval_id) or mock.evaluation()).status
        == "complete",
        msg="eval complete",
    )
    return job


class TestTimeTable:
    def test_witness_and_nearest(self):
        tt = TimeTable(granularity=0.0)
        tt.witness(10, when=100.0)
        tt.witness(20, when=200.0)
        tt.witness(30, when=300.0)
        assert tt.nearest_index(50.0) == 0
        assert tt.nearest_index(150.0) == 10
        assert tt.nearest_index(250.0) == 20
        assert tt.nearest_index(999.0) == 30

    def test_granularity_suppresses(self):
        tt = TimeTable(granularity=10.0)
        tt.witness(1, when=100.0)
        tt.witness(2, when=105.0)  # inside granularity window: dropped
        tt.witness(3, when=120.0)
        assert tt.nearest_index(110.0) == 1
        assert tt.nearest_index(130.0) == 3


class TestForceGC:
    def test_force_gc_reaps_stopped_job(self):
        """Stopped dead job: force GC purges job, evals and allocs
        (core_sched.go jobGC + evalReap)."""
        server = make_server()
        try:
            for _ in range(3):
                server.node_register(mock.node())
            job = run_job(server)
            assert len(server.state.allocs_by_job(job.namespace, job.id)) == 2

            # stop (deregister, no purge): allocs go terminal, job dead
            server.job_deregister(job.namespace, job.id)
            wait_until(
                lambda: all(
                    a.terminal_status()
                    for a in server.state.allocs_by_job(job.namespace, job.id)
                ),
                msg="allocs terminal",
            )
            wait_until(
                lambda: (server.state.job_by_id(job.namespace, job.id)) is None
                or server.state.job_by_id(job.namespace, job.id).status == "dead",
                msg="job dead",
            )

            server.system_gc()
            wait_until(
                lambda: server.state.job_by_id(job.namespace, job.id) is None,
                msg="job purged",
            )
            assert server.state.allocs_by_job(job.namespace, job.id) == []
            assert server.state.evals_by_job(job.namespace, job.id) == []
        finally:
            server.stop()

    def test_force_gc_reaps_down_node(self):
        """Down node with no allocs is deregistered (core_sched.go nodeGC)."""
        server = make_server()
        try:
            node = mock.node()
            server.node_register(node)
            server.node_update_status(node.id, "down")
            server.system_gc()
            wait_until(
                lambda: server.state.node_by_id(node.id) is None,
                msg="node reaped",
            )
        finally:
            server.stop()

    def test_force_gc_spares_live_job(self):
        """A running service job's evals/allocs survive force GC."""
        server = make_server()
        try:
            for _ in range(2):
                server.node_register(mock.node())
            job = run_job(server)
            server.system_gc()
            time.sleep(1.0)
            assert server.state.job_by_id(job.namespace, job.id) is not None
            assert len(server.state.allocs_by_job(job.namespace, job.id)) == 2
        finally:
            server.stop()

    def test_http_system_gc_route(self):
        from nomad_tpu.api.http import HTTPServer

        server = make_server()
        http = HTTPServer(server, port=0)
        http.start()
        try:
            import json
            import urllib.request

            node = mock.node()
            server.node_register(node)
            server.node_update_status(node.id, "down")
            req = urllib.request.Request(
                f"http://127.0.0.1:{http.port}/v1/system/gc",
                data=b"{}",
                method="PUT",
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req) as resp:
                json.loads(resp.read() or b"{}")
            wait_until(
                lambda: server.state.node_by_id(node.id) is None,
                msg="node reaped via HTTP force gc",
            )
        finally:
            http.stop()
            server.stop()


class TestPeriodicGC:
    def test_leader_cron_reaps_on_interval(self):
        """Terminal objects are reaped automatically by the leader's GC cron
        with tiny thresholds (leader.go:440) — the long-running-cluster
        state-size-bounded property."""
        server = make_server(
            {
                "eval_gc_interval": 0.3,
                "job_gc_interval": 0.3,
                "node_gc_interval": 0.3,
                "deployment_gc_interval": 0.3,
                "eval_gc_threshold": 0.0,
                "job_gc_threshold": 0.0,
                "node_gc_threshold": 0.0,
                "time_table_granularity": 0.3,
            }
        )
        try:
            for _ in range(2):
                server.node_register(mock.node())
            job = run_job(server)
            server.job_deregister(job.namespace, job.id)
            wait_until(
                lambda: server.state.job_by_id(job.namespace, job.id) is None,
                timeout=30.0,
                msg="job auto-GC'd by leader cron",
            )
            assert server.state.allocs_by_job(job.namespace, job.id) == []
        finally:
            server.stop()
