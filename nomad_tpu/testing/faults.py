"""Deterministic, seeded fault-injection plane (the Jepsen-style nemesis
for in-process clusters; cf. PAPERS.md partition-testing entries).

A ``FaultPlane`` holds an ordered list of :class:`FaultRule`. Production
seams call the module-level gates at well-known points:

- ``on_rpc(src, dst, method)`` — ConnPool (rpc/client.py) before every
  call: drop, delay, duplicate, or sever the session to ``dst``.
- ``on_raft(src, dst, method)`` — the raft transport
  (raft/transport.py): drop/delay/duplicate AppendEntries, votes, and
  snapshots per (src, dst, method).
- ``fault_point(name)`` — process-level points: ``worker.post_dequeue``
  and ``worker.pre_submit`` (kill a scheduler worker mid-eval),
  ``plan.raft_apply`` (fail/partition the leader mid plan-commit batch),
  ``tpu.kernel`` (device error / NaN at kernel dispatch),
  ``fsm.apply.pre`` / ``fsm.apply.post_state`` (kill -9 around an FSM
  apply — before the applier ran, or after state mutated but before
  events published; the committed-plane crash-recovery storm's seams).
- ``on_region(src_region, dst_region, channel)`` — every INTER-REGION
  link: gossip datagrams (gossip/swim.py), HTTP region forwarding
  (api/http.py) and ACL replication (core/server.py). ``src``/``dst``
  patterns match *region names*, ``method`` matches the channel
  (``gossip`` | ``http.forward`` | ``acl.replication``), so a full
  region partition is ONE declarative rule — not N per-connection
  severs keyed to intra-region transport addresses.

Region-scale helpers: :meth:`FaultPlane.partition_regions` installs the
(symmetric or asymmetric) sever rules for a region pair and returns
them; :meth:`FaultPlane.expire_rules` heals by retiring rules in place
(the rule list order — and therefore the seeded decision sequence of
every other rule — is untouched, keeping replay deterministic).

Every decision is drawn from one seeded ``random.Random`` under a lock,
so a deterministic call sequence yields a deterministic fault schedule.
Rules record ``matches``/``trips`` and the plane keeps a ``log`` of every
injected fault for test assertions.

Install with ``install(FaultPlane(seed=...))`` (or the ``plane()``
context manager) and always ``uninstall()`` — the pointer is global to
the process.
"""

from __future__ import annotations

import contextlib
import fnmatch
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional


class SimulatedCrash(BaseException):
    """A fault-plane "kill -9": derives from BaseException so no ordinary
    ``except Exception`` recovery path (nack handlers, retry loops) can
    observe it — exactly like a process death, the component simply stops
    mid-operation and the cluster's leases/timers must clean up."""


@dataclass
class FaultRule:
    """One match-and-inject rule. Patterns are fnmatch globs; ``scope``
    selects the seam ("rpc", "raft", or "point"). ``action`` is one of
    drop | delay | duplicate | sever | crash | error | callback."""

    scope: str
    action: str
    src: str = "*"
    dst: str = "*"
    method: str = "*"  # RPC/raft method, or the fault-point name
    p: float = 1.0  # trip probability per match (seeded)
    delay: float = 0.0  # seconds, for action == "delay"
    count: Optional[int] = None  # max trips; None = unlimited
    after: int = 0  # skip the first N matches
    error: Optional[BaseException] = None  # payload for action == "error"
    callback: Optional[Callable[[], None]] = None  # runs on every trip
    matches: int = 0
    trips: int = 0

    def _matches(self, scope: str, src: str, dst: str, method: str) -> bool:
        return (
            self.scope == scope
            and fnmatch.fnmatch(src, self.src)
            and fnmatch.fnmatch(dst, self.dst)
            and fnmatch.fnmatch(method, self.method)
        )


class FaultPlane:
    def __init__(self, seed: int = 0):
        self.rng = random.Random(seed)
        # nta: ignore[unbounded-cache] WHY: a plane is scenario-scoped
        # and its rule list is the test's specification
        self.rules: list[FaultRule] = []
        #: every injected fault as (scope, src, dst, method, action)
        # nta: ignore[unbounded-cache] WHY: scenario-scoped assertion
        # surface (tests read it); dies with the plane
        self.log: list[tuple] = []
        self._lock = threading.Lock()

    # -- rule construction ---------------------------------------------
    def rule(self, scope: str, action: str, **kw) -> FaultRule:
        r = FaultRule(scope=scope, action=action, **kw)
        with self._lock:
            self.rules.append(r)
        return r

    def trips(self, scope: Optional[str] = None) -> int:
        with self._lock:
            return sum(
                r.trips for r in self.rules if scope is None or r.scope == scope
            )

    def partition_regions(
        self,
        a: str,
        b: str,
        symmetric: bool = True,
        channel: str = "*",
        **kw,
    ) -> list[FaultRule]:
        """Sever every inter-region channel from region ``a`` to region
        ``b`` (and the reverse when ``symmetric``): gossip goes dark, HTTP
        forwards fail, ACL replication stalls — one declarative rule per
        direction. Heal with :meth:`expire_rules` on the returned list."""
        rules = [self.rule("region", "sever", src=a, dst=b, method=channel, **kw)]
        if symmetric:
            rules.append(
                self.rule("region", "sever", src=b, dst=a, method=channel, **kw)
            )
        return rules

    def expire_rules(self, rules: list[FaultRule]):
        """Retire rules in place (heal): each stops tripping by capping
        ``count`` at its current trip total. Removal would re-index the
        ordered rule list and perturb the seeded decision sequence of
        every later rule — expiry keeps replays byte-stable."""
        with self._lock:
            for r in rules:
                r.count = r.trips

    # -- decision core -------------------------------------------------
    def _decide(
        self, scope: str, src: str, dst: str, method: str,
        exclude: tuple = (),
    ) -> Optional[FaultRule]:
        """First rule that matches AND trips (probability, after, count
        all drawn/checked under the lock for determinism). Rules whose
        action is in ``exclude`` are skipped entirely — no match, no trip
        — so a seam that cannot honor an action (duplicating a stream)
        never falsely reports it injected."""
        with self._lock:
            for r in self.rules:
                if r.action in exclude:
                    continue
                if not r._matches(scope, src, dst, method):
                    continue
                r.matches += 1
                if r.matches <= r.after:
                    continue
                if r.count is not None and r.trips >= r.count:
                    continue
                if r.p < 1.0 and self.rng.random() >= r.p:
                    continue
                r.trips += 1
                self.log.append((scope, src, dst, method, r.action))
                return r
        return None

    def _fire(self, rule: FaultRule, what: str) -> Optional[str]:
        """Run the rule's side effects; returns the action the caller must
        apply itself ("drop"/"duplicate"/"sever"), or None."""
        if rule.callback is not None:
            rule.callback()
        if rule.action == "delay":
            time.sleep(rule.delay)
            return None
        if rule.action == "crash":
            raise SimulatedCrash(what)
        if rule.action == "error":
            raise rule.error if rule.error is not None else RuntimeError(
                f"injected fault: {what}"
            )
        if rule.action == "callback":
            return None
        return rule.action

    # -- seams ----------------------------------------------------------
    def on_rpc(
        self, src: str, dst: str, method: str, exclude: tuple = ()
    ) -> Optional[str]:
        rule = self._decide("rpc", src, dst, method, exclude=exclude)
        if rule is None:
            return None
        return self._fire(rule, f"rpc {src}->{dst} {method}")

    def on_raft(self, src: str, dst: str, method: str) -> Optional[str]:
        rule = self._decide("raft", src, dst, method)
        if rule is None:
            return None
        return self._fire(rule, f"raft {src}->{dst} {method}")

    def on_point(self, point: str) -> Optional[str]:
        rule = self._decide("point", "", "", point)
        if rule is None:
            return None
        return self._fire(rule, point)

    def on_region(
        self, src_region: str, dst_region: str, channel: str
    ) -> Optional[str]:
        """Inter-region link gate. Same-region traffic never matches —
        region rules model the WAN, not the local fabric."""
        if src_region == dst_region:
            return None
        rule = self._decide("region", src_region, dst_region, channel)
        if rule is None:
            return None
        return self._fire(rule, f"region {src_region}->{dst_region} {channel}")


#: the installed plane; production seams read this once per fault point
ACTIVE: Optional[FaultPlane] = None


def install(plane_: FaultPlane) -> FaultPlane:
    global ACTIVE
    ACTIVE = plane_
    return plane_


def uninstall():
    global ACTIVE
    ACTIVE = None


@contextlib.contextmanager
def plane(seed: int = 0):
    p = install(FaultPlane(seed=seed))
    try:
        yield p
    finally:
        uninstall()


def fault_point(point: str):
    """Process-level fault gate: no-op unless a plane is installed and a
    "point"-scoped rule matches ``point``. May sleep (delay), raise
    SimulatedCrash (crash) or an injected error, or run a test callback
    (e.g. partition the leader at exactly this moment)."""
    p = ACTIVE
    if p is not None:
        p.on_point(point)


def region_link(src_region: str, dst_region: str, channel: str) -> Optional[str]:
    """Inter-region link gate for production seams: returns the action
    the seam must apply itself ("drop"/"sever" — both mean the traffic
    does not cross the WAN), or None. May also sleep (delay) or raise
    like any other seam."""
    p = ACTIVE
    if p is None:
        return None
    return p.on_region(src_region or "global", dst_region or "global", channel)
