"""Raft consensus core: leader election, log replication, commitment,
snapshots (the role vendored hashicorp/raft plays in the reference —
nomad/server.go:1075 setupRaft; protocol semantics per the raft paper).

Threading model: one state lock guards term/role/log bookkeeping; a
replicator thread per peer pushes AppendEntries; an apply thread delivers
committed entries to the FSM and resolves proposer futures. The election
timer runs in the main role loop. All waits are condition-based so an
in-process 3-node cluster elects in tens of milliseconds (the same
property the reference's in-memory raft gives its TestServer clusters).
"""

from __future__ import annotations

import logging
import random
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

from .log import CMD, CONFIG, NOOP, InmemLogStore, LogEntry, SnapshotStore, StableStore
from .transport import Transport

logger = logging.getLogger("nomad_tpu.raft")

FOLLOWER = "follower"
CANDIDATE = "candidate"
LEADER = "leader"
SHUTDOWN = "shutdown"


class NotLeaderError(Exception):
    def __init__(self, leader_addr: Optional[str] = None, leader_id: Optional[str] = None):
        super().__init__(f"node is not the leader (leader={leader_id}@{leader_addr})")
        self.leader_addr = leader_addr
        self.leader_id = leader_id


class ApplyTimeout(TimeoutError):
    """The apply wait expired with the entry's outcome still UNKNOWN: it is
    already stored in the leader's log and may yet commit and apply. Callers
    must not treat this as "nothing happened" — a later write computed
    against state missing this entry can double-apply its effects (the plan
    applier resolves the outcome through a barrier instead). Carries the
    entry's log index and the term it was proposed in: a resolver must
    prove the term never changed, or the entry may have been truncated
    under an intervening leader."""

    def __init__(self, index: int, term: int = 0):
        super().__init__(
            f"raft apply timed out (entry {index} term {term} still in flight)"
        )
        self.raft_index = index
        self.raft_term = term


@dataclass
class RaftConfig:
    heartbeat_interval: float = 0.05
    election_timeout_min: float = 0.15
    election_timeout_max: float = 0.30
    snapshot_threshold: int = 8192  # log entries between snapshots
    snapshot_trailing: int = 128  # entries kept behind a snapshot for catch-up
    max_append_entries: int = 64
    apply_timeout: float = 10.0


class _Future:
    __slots__ = ("event", "result", "error")

    def __init__(self):
        self.event = threading.Event()
        self.result = None
        self.error = None

    def resolve(self, result, error=None):
        self.result = result
        self.error = error
        self.event.set()

    def wait(self, timeout):
        if not self.event.wait(timeout):
            raise TimeoutError("raft apply timed out")
        if self.error is not None:
            raise self.error
        return self.result


class Raft:
    def __init__(
        self,
        node_id: str,
        address: str,
        voters: dict[str, str],
        fsm,
        transport: Transport,
        log_store=None,
        stable: Optional[StableStore] = None,
        snapshots: Optional[SnapshotStore] = None,
        config: Optional[RaftConfig] = None,
        on_leadership: Optional[Callable[[bool], None]] = None,
    ):
        self.node_id = node_id
        self.address = address
        self.voters = dict(voters)  # id -> address (must include self)
        self.fsm = fsm
        self.transport = transport
        self.log = log_store if log_store is not None else InmemLogStore()
        self.stable = stable if stable is not None else StableStore()
        self.snapshots = snapshots if snapshots is not None else SnapshotStore()
        self.config = config or RaftConfig()
        self.on_leadership = on_leadership

        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self.current_term = int(self.stable.get("term", 0))
        self.voted_for = self.stable.get("voted_for")
        self.role = FOLLOWER
        self.leader_id: Optional[str] = None
        self.commit_index = 0
        self.last_applied = 0
        self.term_start_index = 0
        self.last_snapshot_index = 0
        self.last_snapshot_term = 0
        self._last_contact = time.monotonic()
        self._futures: dict[int, _Future] = {}
        # nta: ignore[unbounded-cache] WHY: the three per-peer maps
        # below are keyed by voter id — bounded by the configured peer
        # set (membership changes republish the voter map)
        self._match_index: dict[str, int] = {}
        # nta: ignore[unbounded-cache] WHY: per-voter, see above
        self._peer_contact: dict[str, float] = {}  # last successful append ack
        # nta: ignore[unbounded-cache] WHY: per-voter, see above
        self._next_index: dict[str, int] = {}
        self._replicators: dict[str, threading.Thread] = {}
        self._repl_conds: dict[str, threading.Condition] = {}
        self._threads: list[threading.Thread] = []
        self._shutdown = False
        self._leadership_epoch = 0
        # leadership notifications are delivered IN ORDER from a single
        # dispatcher thread — concurrent unordered callbacks could let a
        # stale revoke land after a newer establish on a flap
        self._leadership_queue: list[bool] = []
        self._leadership_cond = threading.Condition()
        # snapshot staged by handle_install_snapshot; the apply thread is
        # the only FSM mutator (apply AND restore), so a restore can never
        # interleave with an in-flight entry apply
        self._pending_snapshot = None

        self._restore_on_boot()
        self.transport.register(
            self.address,
            {
                "request_vote": self.handle_request_vote,
                "append_entries": self.handle_append_entries,
                "install_snapshot": self.handle_install_snapshot,
            },
        )

    # ------------------------------------------------------------------
    def _restore_on_boot(self):
        # The per-line ignores below share one WHY: this runs during
        # construction, before start() spawns any raft thread —
        # pre-spawn publication (Thread.start() is the h-b edge).
        snap = self.snapshots.latest()
        if snap is not None:
            self.fsm.restore(snap.data)
            self.last_snapshot_index = snap.last_index  # nta: ignore[unsynchronized-shared-write]
            self.last_snapshot_term = snap.last_term  # nta: ignore[unsynchronized-shared-write]
            self.commit_index = snap.last_index  # nta: ignore[unsynchronized-shared-write]
            self.last_applied = snap.last_index  # nta: ignore[unsynchronized-shared-write]
            if snap.voters:
                self.voters = dict(snap.voters)  # nta: ignore[unsynchronized-shared-write]
        # adopt the newest CONFIG entry in the log, if any
        for i in range(self.log.first_index(), self.log.last_index() + 1):
            e = self.log.get(i)
            if e is not None and e.etype == CONFIG:
                self.voters = dict(e.data["voters"])  # nta: ignore[unsynchronized-shared-write]

    def start(self):
        t = threading.Thread(target=self._run, daemon=True, name=f"raft-{self.node_id}")
        t.start()
        self._threads.append(t)
        a = threading.Thread(
            target=self._apply_loop, daemon=True, name=f"raft-apply-{self.node_id}"
        )
        a.start()
        self._threads.append(a)
        if self.on_leadership is not None:
            n = threading.Thread(
                target=self._leadership_loop,
                daemon=True,
                name=f"raft-lead-{self.node_id}",
            )
            n.start()
            self._threads.append(n)

    def _notify_leadership(self, leader: bool):
        with self._leadership_cond:
            self._leadership_queue.append(leader)
            self._leadership_cond.notify()

    def _leadership_loop(self):
        while True:
            with self._leadership_cond:
                while not self._leadership_queue and not self._shutdown:
                    self._leadership_cond.wait(0.2)
                if self._shutdown and not self._leadership_queue:
                    return
                leader = self._leadership_queue.pop(0)
                # collapse a flap: only the latest state matters, and
                # delivering stale transitions in order is still correct
            try:
                self.on_leadership(leader)
            except Exception:
                logger.exception("leadership callback failed")

    def shutdown(self):
        with self._cond:
            self._shutdown = True
            self.role = SHUTDOWN
            self._cond.notify_all()
        for c in self._repl_conds.values():
            with c:
                c.notify_all()
        with self._leadership_cond:
            self._leadership_cond.notify_all()
        for f in list(self._futures.values()):
            f.resolve(None, NotLeaderError())
        self._futures.clear()

    # ------------------------------------------------------------------
    # helpers (hold lock)
    # ------------------------------------------------------------------
    def _last_log(self) -> tuple[int, int]:
        li = self.log.last_index()
        if li == 0:
            return self.last_snapshot_index, self.last_snapshot_term
        e = self.log.get(li)
        return li, e.term if e else 0

    def _term_at(self, index: int) -> int:
        if index == 0:
            return 0
        if index == self.last_snapshot_index:
            return self.last_snapshot_term
        e = self.log.get(index)
        return e.term if e is not None else -1

    def _set_term(self, term: int):
        self.current_term = term
        self.voted_for = None
        self.stable.set_many(term=term, voted_for=None)

    def _become_follower(self, term: int, leader_id: Optional[str] = None):
        was_leader = self.role == LEADER
        if term > self.current_term:
            self._set_term(term)
        self.role = FOLLOWER
        if leader_id is not None:
            self.leader_id = leader_id
        self._cond.notify_all()
        if was_leader:
            self._leadership_epoch += 1
            self._fail_pending_futures()
            if self.on_leadership is not None:
                self._notify_leadership(False)

    def _fail_pending_futures(self):
        for f in self._futures.values():
            f.resolve(None, NotLeaderError(self.leader_address(), self.leader_id))
        self._futures.clear()

    def leader_address(self) -> Optional[str]:
        lid = self.leader_id
        return self.voters.get(lid) if lid else None

    def voters_snapshot(self) -> dict[str, str]:
        """Copy of the voter map safe to iterate off-thread (membership
        changes mutate ``voters`` under the raft lock)."""
        with self._lock:
            return dict(self.voters)

    def peer_progress(self) -> dict:
        """Leader-side replication progress per voter (for autopilot
        server-health; ref autopilot ServerStats / raft.Stats)."""
        now = time.monotonic()
        with self._lock:
            last, _ = self._last_log()
            out = {}
            for pid in self.voters:
                if pid == self.node_id:
                    out[pid] = {
                        "match_index": last,
                        "last_contact_s": 0.0,
                        "leader": self.role == LEADER,
                    }
                    continue
                contact = self._peer_contact.get(pid)
                out[pid] = {
                    "match_index": self._match_index.get(pid, 0),
                    "last_contact_s": (
                        round(now - contact, 3) if contact is not None else None
                    ),
                    "leader": False,
                }
            return out

    def is_leader(self) -> bool:
        return self.role == LEADER

    # ------------------------------------------------------------------
    # main role loop
    # ------------------------------------------------------------------
    def _election_timeout(self) -> float:
        return random.uniform(
            self.config.election_timeout_min, self.config.election_timeout_max
        )

    def _run(self):
        while True:
            with self._lock:
                role = self.role
            if role == SHUTDOWN:
                return
            if role == FOLLOWER:
                self._run_follower()
            elif role == CANDIDATE:
                self._run_candidate()
            elif role == LEADER:
                self._run_leader()

    def _run_follower(self):
        timeout = self._election_timeout()
        while True:
            with self._cond:
                if self.role != FOLLOWER:
                    return
                remaining = timeout - (time.monotonic() - self._last_contact)
                if remaining <= 0:
                    if self.node_id not in self.voters:
                        # non-voting joiner (gossip auto-discovery): wait to
                        # be added by the leader via a CONFIG entry instead
                        # of standing for election as a one-node cluster
                        self._last_contact = time.monotonic()
                        continue
                    # no heartbeat: stand for election
                    self.role = CANDIDATE
                    return
                self._cond.wait(remaining)

    def _run_candidate(self):
        with self._lock:
            if self.role != CANDIDATE:
                return
            self._set_term(self.current_term + 1)
            term = self.current_term
            self.voted_for = self.node_id
            self.stable.set("voted_for", self.node_id)
            self.leader_id = None
            last_index, last_term = self._last_log()
            peers = {i: a for i, a in self.voters.items() if i != self.node_id}
            quorum = len(self.voters) // 2 + 1

        votes = [1]  # self-vote
        vote_lock = threading.Lock()
        done = threading.Event()

        def ask(peer_id, addr):
            try:
                resp = self.transport.request_vote(
                    addr,
                    {
                        "_from": self.address,
                        "term": term,
                        "candidate_id": self.node_id,
                        "last_log_index": last_index,
                        "last_log_term": last_term,
                    },
                )
            except Exception:
                return
            with self._lock:
                if resp["term"] > self.current_term:
                    self._become_follower(resp["term"])
                    done.set()
                    return
            if resp.get("granted"):
                with vote_lock:
                    votes[0] += 1
                    if votes[0] >= quorum:
                        done.set()

        threads = [
            threading.Thread(
                target=ask, args=(pid, addr), daemon=True,
                name=f"raft-vote-{pid}",
            )
            for pid, addr in peers.items()
        ]
        for t in threads:
            t.start()
        if not peers:
            done.set()
        done.wait(self._election_timeout())

        with self._lock:
            if self.role != CANDIDATE or self.current_term != term:
                return
            if votes[0] >= quorum:
                self.role = LEADER
                self.leader_id = self.node_id
                logger.info(
                    "raft: %s elected leader (term %d)", self.node_id, term
                )
            # else: loop re-enters candidate with a fresh randomized timeout
            elif self.role == CANDIDATE:
                self.role = FOLLOWER  # back off; follower loop re-times
                self._last_contact = time.monotonic()

    # ------------------------------------------------------------------
    # leader
    # ------------------------------------------------------------------
    def _run_leader(self):
        with self._lock:
            term = self.current_term
            epoch = self._leadership_epoch
            self._replicators.clear()
            self._repl_conds.clear()
            last_index, _ = self._last_log()
            for pid in self.voters:
                if pid == self.node_id:
                    continue
                self._next_index[pid] = last_index + 1
                self._match_index[pid] = 0
            # commit a noop to establish leadership over prior-term entries
            noop = LogEntry(index=last_index + 1, term=term, etype=NOOP, data=None)
            self.log.store_entries([noop])
            #: index of this term's noop: once APPLIED, the FSM provably
            #: covers every entry committed by prior leaders (the
            #: server-level establishment barrier rides it instead of
            #: proposing a second entry)
            self.term_start_index = noop.index
        self._start_replicators(epoch)
        self._maybe_advance_commit()
        if self.on_leadership is not None:
            self._notify_leadership(True)

        # leader loop: watch for step-down
        while True:
            with self._cond:
                if self.role != LEADER or self._shutdown:
                    return
                self._cond.wait(self.config.heartbeat_interval)

    def _start_replicators(self, epoch: int):
        with self._lock:
            peers = {i: a for i, a in self.voters.items() if i != self.node_id}
        for pid, addr in peers.items():
            cond = threading.Condition()
            self._repl_conds[pid] = cond
            t = threading.Thread(
                target=self._replicate_loop,
                args=(pid, addr, epoch, cond),
                daemon=True,
                name=f"raft-repl-{self.node_id}->{pid}",
            )
            self._replicators[pid] = t
            t.start()

    def _replicate_loop(self, peer_id: str, addr: str, epoch: int, cond):
        backoff = 0.01
        # WHY: raft replication IS the recovery path — one loop per peer,
        # capped backoff; budget-severing it turns overload into
        # unavailability, the opposite of shedding
        while True:  # nta: ignore[retry-without-budget]
            with self._lock:
                if (
                    self.role != LEADER
                    or self._leadership_epoch != epoch
                    or self._shutdown
                    or peer_id not in self.voters  # removed by remove_voter
                ):
                    self._replicators.pop(peer_id, None)
                    self._repl_conds.pop(peer_id, None)
                    return
                term = self.current_term
                next_idx = self._next_index.get(peer_id, 1)
                need_snapshot = (
                    next_idx <= self.last_snapshot_index
                    and self.log.get(next_idx) is None
                )

            try:
                if need_snapshot:
                    self._send_snapshot(peer_id, addr, term)
                    backoff = 0.01
                else:
                    ok = self._send_append(peer_id, addr, term, next_idx)
                    backoff = 0.01 if ok else min(backoff * 2, 0.5)
            except Exception:
                time.sleep(backoff)
                backoff = min(backoff * 2, 0.5)

            # wait for new entries or the heartbeat tick
            with cond:
                cond.wait(self.config.heartbeat_interval)

    def _send_append(self, peer_id, addr, term, next_idx) -> bool:
        with self._lock:
            prev_index = next_idx - 1
            prev_term = self._term_at(prev_index)
            entries = []
            last = self.log.last_index()
            i = next_idx
            while i <= last and len(entries) < self.config.max_append_entries:
                e = self.log.get(i)
                if e is None:
                    break
                entries.append([e.index, e.term, e.etype, e.data])
                i += 1
            commit = self.commit_index
        resp = self.transport.append_entries(
            addr,
            {
                "_from": self.address,
                "term": term,
                "leader_id": self.node_id,
                "prev_log_index": prev_index,
                "prev_log_term": prev_term,
                "entries": entries,
                "leader_commit": commit,
            },
        )
        with self._lock:
            if resp["term"] > self.current_term:
                self._become_follower(resp["term"])
                return False
            if self.role != LEADER:
                return False
            if resp.get("success"):
                self._peer_contact[peer_id] = time.monotonic()
                if entries:
                    self._match_index[peer_id] = entries[-1][0]
                    self._next_index[peer_id] = entries[-1][0] + 1
                else:
                    self._match_index[peer_id] = max(
                        self._match_index.get(peer_id, 0), prev_index
                    )
        if resp.get("success"):
            self._maybe_advance_commit()
            return True
        with self._lock:
            hint = resp.get("conflict_index")
            self._next_index[peer_id] = max(
                1, hint if hint else self._next_index.get(peer_id, 2) - 1
            )
        return False

    def _send_snapshot(self, peer_id, addr, term):
        snap = self.snapshots.latest()
        if snap is None:
            return
        resp = self.transport.install_snapshot(
            addr,
            {
                "_from": self.address,
                "term": term,
                "leader_id": self.node_id,
                "last_index": snap.last_index,
                "last_term": snap.last_term,
                "voters": snap.voters or self.voters,
                "data": snap.data,
            },
        )
        with self._lock:
            if resp["term"] > self.current_term:
                self._become_follower(resp["term"])
                return
            self._match_index[peer_id] = snap.last_index
            self._next_index[peer_id] = snap.last_index + 1

    def _maybe_advance_commit(self):
        notify = False
        with self._lock:
            if self.role != LEADER:
                return
            last = self.log.last_index()
            for n in range(last, self.commit_index, -1):
                e = self.log.get(n)
                if e is None or e.term != self.current_term:
                    break  # only commit current-term entries by counting
                votes = 1  # self
                for pid in self.voters:
                    if pid == self.node_id:
                        continue
                    if self._match_index.get(pid, 0) >= n:
                        votes += 1
                if votes >= len(self.voters) // 2 + 1:
                    self.commit_index = n
                    notify = True
                    break
            if notify:
                self._cond.notify_all()

    # ------------------------------------------------------------------
    # apply pipeline
    # ------------------------------------------------------------------
    def _apply_loop(self):
        while True:
            with self._cond:
                while (
                    self.last_applied >= self.commit_index
                    and self._pending_snapshot is None
                    and not self._shutdown
                ):
                    self._cond.wait(0.2)
                if self._shutdown:
                    return
                pending = self._pending_snapshot
                self._pending_snapshot = None
            if pending is not None:
                data, last_index, last_term = pending
                self.fsm.restore(data)
                with self._cond:
                    self.last_snapshot_index = last_index
                    self.last_snapshot_term = last_term
                    if self.last_applied < last_index:
                        self.last_applied = last_index
                    self._cond.notify_all()
                continue
            with self._cond:
                # one entry at a time: a concurrent InstallSnapshot may jump
                # last_applied forward, and re-reading under the lock keeps
                # this loop from double-applying pre-snapshot entries
                i = self.last_applied + 1
                e = self.log.get(i)
                if e is None:
                    # compacted/cleared beneath us (snapshot install):
                    # skip forward rather than spinning
                    if i <= self.last_snapshot_index:
                        self.last_applied = self.last_snapshot_index
                    else:
                        self._cond.wait(0.05)
                    continue
            result, error = None, None
            if e.etype == CMD:
                msg_type, payload = e.data
                try:
                    result = self.fsm.apply(i, msg_type, payload)
                except Exception as exc:  # surfaced to the proposer
                    logger.exception("fsm apply failed at index %d", i)
                    error = exc
            elif e.etype == CONFIG:
                pass  # voters adopted at append time
            with self._lock:
                # if a snapshot install advanced past us while we applied,
                # keep the further-ahead value (its state already contains
                # this entry's effect)
                if self.last_applied < i:
                    self.last_applied = i
                fut = self._futures.pop(i, None)
            if fut is not None:
                fut.resolve(result, error)
            self._maybe_snapshot()

    def _maybe_snapshot(self):
        with self._lock:
            applied_since = self.last_applied - self.last_snapshot_index
            if applied_since < self.config.snapshot_threshold:
                return
            last_applied = self.last_applied
            term = self._term_at(last_applied)
            voters = dict(self.voters)
        data = self.fsm.snapshot()
        from .log import Snapshot

        self.snapshots.save(
            Snapshot(
                last_index=last_applied,
                last_term=term if term > 0 else self.current_term,
                data=data,
                voters=voters,
            )
        )
        with self._lock:
            self.last_snapshot_index = last_applied
            self.last_snapshot_term = term
            trail_lo = self.log.first_index()
            trail_hi = last_applied - self.config.snapshot_trailing
            if trail_lo and trail_hi >= trail_lo:
                self.log.delete_range(trail_lo, trail_hi)

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------
    def apply(self, msg_type: str, payload, timeout: Optional[float] = None):
        """Propose an FSM command; blocks until committed+applied and
        returns the FSM response (ref nomad/rpc.go raftApply)."""
        fut = _Future()
        with self._lock:
            if self.role != LEADER:
                raise NotLeaderError(self.leader_address(), self.leader_id)
            index = self.log.last_index() + 1
            entry = LogEntry(
                index=index, term=self.current_term, etype=CMD,
                data=[msg_type, payload],
            )
            self.log.store_entries([entry])
            self._futures[index] = fut
        self._kick_replicators()
        self._maybe_advance_commit()
        try:
            return fut.wait(timeout or self.config.apply_timeout)
        except ApplyTimeout:
            raise
        except TimeoutError:
            raise ApplyTimeout(index, entry.term) from None

    def barrier(self, timeout: Optional[float] = None):
        """Commit + apply a noop, guaranteeing all prior entries applied."""
        return self.apply("noop", {}, timeout=timeout)

    def add_voter(self, node_id: str, address: str, timeout: float = 5.0):
        """Single-server membership change via a CONFIG entry (adopted at
        append time, as in standard single-server-change raft)."""
        fut = _Future()
        with self._lock:
            if self.role != LEADER:
                raise NotLeaderError(self.leader_address(), self.leader_id)
            voters = dict(self.voters)
            voters[node_id] = address
            index = self.log.last_index() + 1
            entry = LogEntry(
                index=index, term=self.current_term, etype=CONFIG,
                data={"voters": voters},
            )
            self.log.store_entries([entry])
            self.voters = voters
            self._futures[index] = fut
        self._kick_replicators_new_peer()
        self._maybe_advance_commit()
        fut.wait(timeout)

    def remove_voter(self, node_id: str, timeout: float = 5.0):
        """Single-server membership removal via a CONFIG entry (the
        dead-server cleanup autopilot performs in the reference)."""
        fut = _Future()
        with self._lock:
            if self.role != LEADER:
                raise NotLeaderError(self.leader_address(), self.leader_id)
            if node_id not in self.voters:
                return
            voters = dict(self.voters)
            del voters[node_id]
            index = self.log.last_index() + 1
            entry = LogEntry(
                index=index, term=self.current_term, etype=CONFIG,
                data={"voters": voters},
            )
            self.log.store_entries([entry])
            self.voters = voters
            self._futures[index] = fut
        # wake every replicator: the removed peer's loop observes its
        # eviction and exits instead of retrying a dead address forever
        self._kick_replicators()
        self._maybe_advance_commit()
        fut.wait(timeout)

    def _kick_replicators(self):
        for cond in self._repl_conds.values():
            with cond:
                cond.notify_all()

    def _kick_replicators_new_peer(self):
        with self._lock:
            epoch = self._leadership_epoch
            missing = [
                (pid, addr)
                for pid, addr in self.voters.items()
                if pid != self.node_id and pid not in self._replicators
            ]
            last_index, _ = self._last_log()
            for pid, _ in missing:
                self._next_index[pid] = max(1, last_index)
                self._match_index[pid] = 0
        for pid, addr in missing:
            cond = threading.Condition()
            self._repl_conds[pid] = cond
            t = threading.Thread(
                target=self._replicate_loop,
                args=(pid, addr, epoch, cond),
                daemon=True,
                name=f"raft-repl-{pid}",
            )
            self._replicators[pid] = t
            t.start()
        self._kick_replicators()

    # ------------------------------------------------------------------
    # RPC handlers (invoked by the transport)
    # ------------------------------------------------------------------
    def handle_request_vote(self, req: dict) -> dict:
        with self._lock:
            if req["term"] < self.current_term:
                return {"term": self.current_term, "granted": False}
            if req["term"] > self.current_term:
                self._become_follower(req["term"])
            last_index, last_term = self._last_log()
            up_to_date = req["last_log_term"] > last_term or (
                req["last_log_term"] == last_term
                and req["last_log_index"] >= last_index
            )
            if up_to_date and self.voted_for in (None, req["candidate_id"]):
                self.voted_for = req["candidate_id"]
                self.stable.set("voted_for", self.voted_for)
                self._last_contact = time.monotonic()
                return {"term": self.current_term, "granted": True}
            return {"term": self.current_term, "granted": False}

    def handle_append_entries(self, req: dict) -> dict:
        with self._cond:
            if req["term"] < self.current_term:
                return {"term": self.current_term, "success": False}
            if req["term"] > self.current_term or self.role != FOLLOWER:
                self._become_follower(req["term"], req["leader_id"])
            self.leader_id = req["leader_id"]
            self._last_contact = time.monotonic()

            prev_index, prev_term = req["prev_log_index"], req["prev_log_term"]
            if prev_index > 0:
                local_term = self._term_at(prev_index)
                if local_term == -1:
                    # missing entirely: hint the leader where our log ends
                    last_index, _ = self._last_log()
                    return {
                        "term": self.current_term,
                        "success": False,
                        "conflict_index": last_index + 1,
                    }
                if local_term != prev_term and prev_index > self.last_snapshot_index:
                    # conflicting entry: find first index of that term
                    ci = prev_index
                    while (
                        ci > self.log.first_index()
                        and self._term_at(ci - 1) == local_term
                    ):
                        ci -= 1
                    self.log.delete_range(prev_index, self.log.last_index())
                    return {
                        "term": self.current_term,
                        "success": False,
                        "conflict_index": ci,
                    }

            new_entries = []
            for index, term, etype, data in req["entries"]:
                existing = self.log.get(index)
                if existing is not None:
                    if existing.term == term:
                        continue
                    self.log.delete_range(index, self.log.last_index())
                e = LogEntry(index=index, term=term, etype=etype, data=data)
                new_entries.append(e)
                if etype == CONFIG:
                    self.voters = dict(data["voters"])
            if new_entries:
                self.log.store_entries(new_entries)

            if req["leader_commit"] > self.commit_index:
                last_index, _ = self._last_log()
                self.commit_index = min(req["leader_commit"], last_index)
                self._cond.notify_all()
            return {"term": self.current_term, "success": True}

    def handle_install_snapshot(self, req: dict) -> dict:
        with self._cond:
            if req["term"] < self.current_term:
                return {"term": self.current_term}
            self._become_follower(req["term"], req["leader_id"])
            self._last_contact = time.monotonic()
            if req["last_index"] <= self.last_snapshot_index:
                return {"term": self.current_term}
            first = self.log.first_index()
            if first:
                self.log.delete_range(first, self.log.last_index())
            # stage for the apply thread (the only FSM mutator); raft
            # bookkeeping advances now so replication can proceed, and the
            # apply loop installs the FSM state before touching any entry
            # appended after the snapshot
            self._pending_snapshot = (
                req["data"], req["last_index"], req["last_term"],
            )
            self.last_snapshot_index = req["last_index"]
            self.last_snapshot_term = req["last_term"]
            self.commit_index = req["last_index"]
            if req.get("voters"):
                self.voters = dict(req["voters"])
            self._cond.notify_all()
            return {"term": self.current_term}

    # ------------------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            return {
                "state": self.role,
                "term": self.current_term,
                "leader_id": self.leader_id,
                "commit_index": self.commit_index,
                "last_applied": self.last_applied,
                "last_log_index": self.log.last_index(),
                "last_snapshot_index": self.last_snapshot_index,
                "num_peers": len(self.voters) - 1,
            }
